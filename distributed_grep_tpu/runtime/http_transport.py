"""Worker-side HTTP transport: long-poll control plane + HTTP data plane.

The client half of http_coordinator.py — implements the Transport protocol
(runtime/transport.py) over urllib, replacing the reference's per-call TCP
dials to a hardcoded coordinator IP (worker.go:220-233) and its SFTP file
pushes.  Unlike the reference worker, which dies via log.Fatal when the
coordinator disappears (worker.go:223), this transport retries transient
errors with backoff and raises CoordinatorGone only after the retry budget,
letting the worker loop exit cleanly (the coordinator vanishing after job
completion is the normal shutdown signal, as in the reference).
"""

from __future__ import annotations

import json
import os
import socket
import time
import urllib.error
import urllib.parse
import urllib.request
from pathlib import Path

from distributed_grep_tpu.runtime import rpc
from distributed_grep_tpu.utils.config import JobConfig
from distributed_grep_tpu.utils.logging import get_logger

log = get_logger("http_transport")

RETRY_BUDGET_S = 15.0
RETRY_DELAY_S = 0.5


class CoordinatorGone(Exception):
    """The coordinator stopped answering — treat as job over (worker exits)."""


class HttpTransport:
    def __init__(self, addr: str, rpc_timeout_s: float = 60.0):
        # addr: "host:port" or full "http://host:port".  rpc_timeout_s is the
        # client socket timeout; the coordinator derives its long-poll window
        # as half of this (bounded to 30s, http_coordinator.long_poll_window_s)
        # so a healthy idle long-poll always returns before the socket times
        # out.  Pass the job's JobConfig.rpc_timeout_s.
        if not addr.startswith("http"):
            addr = f"http://{addr}"
        self.base = addr.rstrip("/")
        self.rpc_timeout_s = rpc_timeout_s

    # ------------------------------------------------------------- plumbing
    def _request(self, method: str, path: str, body: bytes | None = None) -> bytes:
        import http.client

        url = f"{self.base}{path}"
        deadline: float | None = None  # anchored at the FIRST failure
        while True:
            req = urllib.request.Request(url, data=body, method=method)
            if body is not None:
                req.add_header("Content-Type", "application/json")
            try:
                with urllib.request.urlopen(req, timeout=self.rpc_timeout_s) as resp:
                    return resp.read()
            except urllib.error.HTTPError as e:
                # Server answered: 4xx/5xx are not liveness failures.
                raise RuntimeError(f"{method} {path} -> {e.code}: {e.read()[:200]!r}") from e
            except (urllib.error.URLError, socket.timeout, ConnectionError,
                    http.client.HTTPException, OSError) as e:
                # HTTPException covers IncompleteRead: the coordinator died
                # mid-body — a liveness failure like any connection error
                now = time.monotonic()
                if deadline is None:
                    deadline = now + RETRY_BUDGET_S
                if now >= deadline:
                    raise CoordinatorGone(f"{method} {path}: {e}") from e
                time.sleep(RETRY_DELAY_S)

    def _rpc(self, verb: str, payload: dict) -> dict:
        data = self._request("POST", f"/rpc/{verb}", json.dumps(payload).encode("utf-8"))
        return json.loads(data)

    # ------------------------------------------------------- control plane
    def assign_task(self, args: rpc.AssignTaskArgs) -> rpc.AssignTaskReply:
        return rpc.AssignTaskReply(**self._rpc(rpc.Verb.ASSIGN_TASK, rpc.to_dict(args)))

    def map_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply:
        return rpc.TaskFinishedReply(**self._rpc(rpc.Verb.MAP_FINISHED, rpc.to_dict(args)))

    def reduce_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply:
        return rpc.TaskFinishedReply(**self._rpc(rpc.Verb.REDUCE_FINISHED, rpc.to_dict(args)))

    def reduce_next_file(self, args: rpc.ReduceNextFileArgs) -> rpc.ReduceNextFileReply:
        return rpc.ReduceNextFileReply(
            **self._rpc(rpc.Verb.REDUCE_NEXT_FILE, rpc.to_dict(args))
        )

    def heartbeat(self, args: rpc.HeartbeatArgs) -> float | None:
        """Advisory stamp; never raises — transport failure surfaces
        through the task's own RPCs.  Plain stamps are single-shot (a
        missed one costs at most one sweep window, and a retry budget
        inside the progress callback would stall the very work being
        stamped); GRACE stamps get a short bounded retry, because a lost
        grace declaration costs the whole silent phase it covers — the
        caller is about to block on a compile anyway, so a few seconds of
        retry cannot stall anything the compile wasn't already stalling.

        Returns the measured round trip of the successful POST (seconds) —
        retry sleeps excluded, so it is the clean RTT sample the span
        pipeline's clock sync wants — or None when every attempt failed."""
        attempts = 3 if args.grace_s > 0 else 1
        for i in range(attempts):
            if args.sent_at > 0:
                # re-stamp per attempt: a retry shipping the FIRST
                # attempt's sent_at would feed the clock sync a timestamp
                # stale by the failed attempt's timeout, skewing the
                # worker's offset estimate by seconds (spans_seq is
                # unchanged, so the span batch still dedups)
                args.sent_at = time.time()
            body = json.dumps(rpc.to_dict(args)).encode("utf-8")
            try:
                req = urllib.request.Request(
                    f"{self.base}/rpc/{rpc.Verb.HEARTBEAT}", data=body,
                    method="POST",
                )
                req.add_header("Content-Type", "application/json")
                t0 = time.monotonic()
                with urllib.request.urlopen(req, timeout=5.0):
                    return time.monotonic() - t0
            except Exception:  # noqa: BLE001 — advisory by contract
                if i + 1 < attempts:
                    time.sleep(0.5)
        return None

    # ---------------------------------------------------------- data plane
    def _data_path(self, kind: str, name: str) -> str:
        """URL path of one data-plane object.  The service transport
        (ServiceHttpTransport) overrides this with a job-scoped prefix —
        every data-plane method routes through here so the two can never
        diverge on an endpoint."""
        return f"/data/{kind}/{urllib.parse.quote(name, safe='')}"

    def read_input(self, filename: str) -> bytes:
        return self._request("GET", self._data_path("input", filename))

    def read_input_path(self, filename: str):
        """(local_path, is_temp): stream the split to a spool file so the
        worker never holds the whole input in memory (streaming apps then
        scan it in bounded chunks).  Same liveness retry policy as
        _request (incl. IncompleteRead: coordinator died mid-body); a
        partial download is discarded and restarted.  Spool dir: the
        DGREP_SPOOL_DIR env var, else the system temp dir — point it at a
        disk-backed path on hosts where /tmp is RAM-backed tmpfs, or the
        spool itself would consume the RAM the streaming path protects."""
        import errno
        import http.client
        import shutil
        import tempfile

        spool_dir = os.environ.get("DGREP_SPOOL_DIR") or None
        url = f"{self.base}{self._data_path('input', filename)}"
        deadline: float | None = None
        tmp = tempfile.NamedTemporaryFile(
            prefix="dgrep-in-", dir=spool_dir, delete=False
        )
        try:
            while True:
                try:
                    req = urllib.request.Request(url)
                    got = tmp.tell()
                    if got:
                        # resume after a mid-body death: the coordinator
                        # serves 'bytes=N-' prefix ranges (206); a 200 means
                        # no range support — start the spool over
                        req.add_header("Range", f"bytes={got}-")
                    with urllib.request.urlopen(req, timeout=self.rpc_timeout_s) as resp:
                        if got and resp.status != 206:
                            tmp.seek(0)
                            tmp.truncate()
                        shutil.copyfileobj(resp, tmp, length=1 << 20)
                    tmp.close()
                    return Path(tmp.name), True
                except urllib.error.HTTPError as e:
                    raise RuntimeError(f"GET {url} -> {e.code}") from e
                except (urllib.error.URLError, socket.timeout, ConnectionError,
                        http.client.HTTPException, OSError) as e:
                    # Local disk problems are NOT liveness failures — retrying
                    # the download cannot fix a full spool disk; surface them.
                    if isinstance(e, OSError) and e.errno in (
                        errno.ENOSPC, errno.EDQUOT, errno.EROFS,
                    ):
                        raise
                    now = time.monotonic()
                    if deadline is None:
                        deadline = now + RETRY_BUDGET_S
                    if now >= deadline:
                        raise CoordinatorGone(f"GET {url}: {e}") from e
                    time.sleep(RETRY_DELAY_S)
        except BaseException:
            tmp.close()
            os.unlink(tmp.name)
            raise

    def write_intermediate(self, name: str, data: bytes) -> None:
        self._request("PUT", self._data_path("intermediate", name), data)

    def read_intermediate(self, name: str) -> bytes:
        return self._request("GET", self._data_path("intermediate", name))

    def write_output(self, name: str, data: bytes) -> None:
        self._request("PUT", self._data_path("out", name), data)

    def publish_task_commit(self, kind: str, task_id: int, attempt: str,
                            payload: dict) -> None:
        """Publish the per-task commit record (runtime/store.py) on the
        coordinator's store — the durable commit the scheduler registers
        from, sent BEFORE the finished RPC."""
        name = f"{kind}-{task_id}.{attempt}"
        self._request(
            "PUT", self._data_path("commit", name),
            json.dumps(payload).encode("utf-8"),
        )

    def write_output_from_file(self, name: str, path: str) -> None:
        """Streaming PUT: the body is a file object sent in blocks with an
        explicit Content-Length (http.client streams ~8 KB at a time), so a
        reduce output larger than worker RAM commits without ever being
        held whole.  Same liveness/retry policy as _request; each retry
        reopens the file from the start."""
        import http.client

        url = f"{self.base}{self._data_path('out', name)}"
        size = os.path.getsize(path)
        deadline: float | None = None
        while True:
            try:
                with open(path, "rb") as f:
                    req = urllib.request.Request(url, data=f, method="PUT")
                    req.add_header("Content-Length", str(size))
                    with urllib.request.urlopen(req, timeout=self.rpc_timeout_s):
                        return
            except urllib.error.HTTPError as e:
                raise RuntimeError(
                    f"PUT {url} -> {e.code}: {e.read()[:200]!r}"
                ) from e
            except (urllib.error.URLError, socket.timeout, ConnectionError,
                    http.client.HTTPException, OSError) as e:
                now = time.monotonic()
                if deadline is None:
                    deadline = now + RETRY_BUDGET_S
                if now >= deadline:
                    raise CoordinatorGone(f"PUT {url}: {e}") from e
                time.sleep(RETRY_DELAY_S)

    # ------------------------------------------------------------ bootstrap
    def fetch_config(self) -> JobConfig:
        return JobConfig(**json.loads(self._request("GET", "/config")))

    def fetch_status(self) -> dict:
        return json.loads(self._request("GET", "/status"))


class ServiceHttpTransport(HttpTransport):
    """HttpTransport against the service daemon (runtime/service.py): the
    control plane is identical, but the data plane is scoped per job —
    ``/data/<job>/<kind>/<name>`` — and follows the worker's current
    assignment via bind_job (runtime/worker._bind_assignment).  A worker
    attached this way serves a STREAM of jobs through one connection."""

    def __init__(self, addr: str, rpc_timeout_s: float = 60.0):
        super().__init__(addr, rpc_timeout_s=rpc_timeout_s)
        self._job = ""

    def bind_job(self, job_id: str) -> None:
        self._job = job_id

    def _data_path(self, kind: str, name: str) -> str:
        if not self._job:
            return super()._data_path(kind, name)
        return (
            f"/data/{urllib.parse.quote(self._job, safe='')}"
            f"/{kind}/{urllib.parse.quote(name, safe='')}"
        )


def run_http_worker(addr: str, n_parallel: int = 1) -> None:
    """CLI worker entry: fetch config, load the application, run task loops.

    The reference worker gets its application as a .so path on argv
    (worker_launch.go:11-19) and everything else from hardcoded constants;
    here the coordinator's /config endpoint supplies both the application
    module spec and the job options.  n_parallel > 1 runs several task loops
    sharing one process — the slot analogue of multiple chips per host.
    """
    import threading

    from distributed_grep_tpu.apps.loader import load_application
    from distributed_grep_tpu.runtime.worker import WorkerLoop

    # Multi-host pod slices: when the standard JAX env vars are present
    # (JAX_COORDINATOR_ADDRESS / _NUM_PROCESSES / _PROCESS_ID), wire
    # jax.distributed before any backend touch so this worker's chips join
    # the global mesh (parallel/multihost.py); single-host runs skip it.
    from distributed_grep_tpu.parallel.multihost import init_distributed

    init_distributed()

    transport = HttpTransport(addr)
    try:
        config = transport.fetch_config()
    except CoordinatorGone:
        log.error("no coordinator at %s", addr)
        raise SystemExit(1)
    # Service daemon detection (runtime/service.py): its /status answers
    # {"service": true}; such workers scope their data plane per job and
    # resolve the application per assignment instead of from /config.
    is_service = False
    try:
        is_service = bool(transport.fetch_status().get("service"))
    except Exception:  # noqa: BLE001 — plain coordinator without /status? no
        pass
    app = load_application(config.application, **config.app_options)
    transport_cls = ServiceHttpTransport if is_service else HttpTransport
    if is_service:
        log.info("attached to a service daemon at %s", addr)

    from distributed_grep_tpu.utils import spans as spans_mod

    def run_loop(slot: int) -> None:
        loop = WorkerLoop(
            transport_cls(addr, rpc_timeout_s=config.rpc_timeout_s),
            app,
            reduce_memory_bytes=config.reduce_memory_bytes,
            # config.spill_dir is a coordinator-host path; HTTP workers only
            # honor it when explicitly set (operators ensure it exists)
            spill_dir=config.spill_dir,
            # span pipeline: the coordinator's /config decides (its side
            # persists events.jsonl; a worker shipping spans nobody stores
            # would be pure payload), DGREP_SPANS forces on for debugging
            spans_enabled=spans_mod.enabled(config.spans),
            job_id=config.effective_job_id(),
        )
        try:
            loop.run()
        except CoordinatorGone:
            # Coordinator exited (job presumably done) — clean worker exit,
            # unlike the reference's log.Fatal (worker.go:223).
            log.info("slot %d: coordinator gone, exiting", slot)

    threads = [
        threading.Thread(target=run_loop, args=(i,), name=f"slot-{i}") for i in range(n_parallel)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

"""Control-plane message schema — the four-verb protocol.

Mirrors map_reduce/rpc.go exactly in capability:

  AssignTask      (rpc.go:10-21)  worker asks for work; long-polls until a
                                  map split or reduce partition is available.
  MapFinished     (rpc.go:23-31)  map task commit notification.
  ReduceFinished  (rpc.go:23-31)  reduce task commit notification.
  ReduceNextFile  (rpc.go:33-42)  streaming shuffle feed: reducer asks for
                                  its next intermediate file, long-polling
                                  until one commits or the map phase ends.

Additions over the reference: an explicit JOB_DONE assignment (the reference
kills workers by closing SSH and letting call() log.Fatal,
coordinator.go:291-296 / worker.go:223) and the grep job options rider on
AssignTaskReply (the pattern plumbing the reference's TODO never built).
All messages are plain dicts <-> dataclasses for JSON transport.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any


class Verb:
    ASSIGN_TASK = "AssignTask"
    MAP_FINISHED = "MapFinished"
    REDUCE_FINISHED = "ReduceFinished"
    REDUCE_NEXT_FILE = "ReduceNextFile"
    # Mid-task liveness stamp (UpdateTimestamp, coordinator.go:176-182 —
    # which the reference exposes but its worker never calls mid-map; here
    # the engine's progress callback drives it so long maps survive a tight
    # failure-detector window, VERDICT r3 item 3).
    HEARTBEAT = "Heartbeat"


class Assignment:
    MAP = "map"
    REDUCE = "reduce"
    JOB_DONE = "job_done"  # explicit shutdown; reference has none


@dataclass
class AssignTaskArgs:
    worker_id: int = -1  # -1 = not yet registered; coordinator allocates
    # Peer-to-peer shuffle (round 16, runtime/peer.py): the worker's
    # advertised shuffle data endpoint ("http://host:port"), shipped on
    # every poll so the service worker table can show who holds shuffle
    # state before an operator drains a worker.  "" (elided from the
    # wire) everywhere peer shuffle is off — payloads then stay
    # byte-identical to the pre-peer protocol.
    peer_endpoint: str = ""


@dataclass
class AssignTaskReply:
    assignment: str = Assignment.JOB_DONE
    # Service multiplexing (runtime/service.py): the job this task belongs
    # to, and the application module spec to run it with — a worker
    # attached to the service daemon serves a STREAM of jobs, so both ride
    # the assignment instead of the one-shot /config bootstrap.  Empty on
    # single-job coordinators (elided from the wire — old peers interop).
    job_id: str = ""
    application: str = ""
    filename: str = ""
    # Multi-file map split (runtime/job.plan_map_splits — cross-file
    # batching of the many-small-files regime): the member files of a
    # batched split, in order.  Empty for ordinary single-file tasks
    # (elided from the wire — old peers interop until batching is used);
    # when set, ``filename`` carries the split's display label, not a
    # readable path.
    filenames: list[str] = field(default_factory=list)
    task_id: int = -1
    n_reduce: int = 0
    worker_id: int = -1
    app_options: dict[str, Any] = field(default_factory=dict)
    # The coordinator's failure-detector window for this task — the worker
    # derives its mid-task heartbeat cadence from it (~window/3), so the
    # two knobs can never drift apart across config changes.
    task_timeout_s: float = 10.0
    # Client backoff hint on "retry" replies (worker quarantine,
    # runtime/scheduler.WorkerHealth): "expect no work for this many
    # seconds" — the worker sleeps (bounded) instead of re-entering the
    # long-poll immediately.  0 on ordinary retries (elided from the
    # wire — old peers interop).
    retry_after_s: float = 0.0
    # Scheduler-incarnation fence (round 10): a fresh random tag per
    # Scheduler construction, echoed by the reducer's shuffle fetches —
    # a reduce attempt that outlives a coordinator/daemon restart holds
    # a files_processed cursor over the OLD task_files arrival order,
    # and serving it from the rebuilt list would feed it duplicate or
    # missing shuffle files (its commit could then WIN resolution with
    # wrong bytes).  Mismatched epochs abort the attempt instead.
    # "" on the wire for old peers (elided).
    epoch: str = ""
    # Cross-tenant scan fusion (round 13, runtime/fusion.py): co-tenant
    # map tasks riding THIS assignment — one worker scan serves every
    # participant, each committed through its own job's data plane and
    # scheduler.  Entries are dicts shaped like a map assignment
    # ({job_id, task_id, filename, filenames, n_reduce, app_options,
    # task_timeout_s, epoch}).  Empty (and elided from the wire, see
    # reply_to_dict) everywhere fusion is off or ineligible — payloads
    # then stay byte-identical to the pre-fusion protocol.
    fused: list = field(default_factory=list)


@dataclass
class TaskFinishedArgs:
    task_id: int
    # Service multiplexing: which job's scheduler this completion belongs
    # to (echoed from the assignment's job_id; empty = the single-job
    # coordinator, elided from the wire).
    job_id: str = ""
    worker_id: int = -1
    # Reduce partitions for which this map task actually produced records —
    # the coordinator registers only files that exist (coordinator.go:139-141).
    produced_parts: list[int] = field(default_factory=list)
    # Span-pipeline piggyback (utils/spans.py): the worker's final span
    # flush for this task, plus a counters snapshot.  Optional fields with
    # defaults, ELIDED from the wire when empty (to_dict below) — old
    # workers and span-disabled runs produce byte-identical payloads.
    # spans_seq is the worker's batch counter for this flush: transport
    # retries reship the same (worker_id, spans_seq) and the coordinator
    # persists it once.
    spans: list[dict] = field(default_factory=list)
    spans_seq: int = -1
    metrics: dict[str, float] | None = None
    # Peer-to-peer shuffle (round 16): a map commit that kept its output
    # on the PRODUCING worker's local spool registers metadata instead of
    # bytes — the worker's shuffle endpoint and per-partition
    # {partition: [size, crc32-hex]} self-checksums (the NonAtomicStore
    # commit-record shape).  The same metadata rides the per-task commit
    # record (the durable unit of truth); these args are the fallback for
    # transports without commit records — and, deliberately, the LIVE
    # attempt's truth when a re-executed map replaces a vanished
    # producer (the resolved record may still name the dead attempt's
    # endpoint; the freshly finished attempt's args self-heal it).
    # Empty/None (elided) on relay commits — pre-peer payloads are
    # byte-identical.
    peer_endpoint: str = ""
    peer_parts: dict | None = None


@dataclass
class TaskFinishedReply:
    ok: bool = True


@dataclass
class ReduceNextFileArgs:
    task_id: int
    files_processed: int  # rpc.go:35 FilesProcessed — resume-safe cursor
    job_id: str = ""  # service multiplexing (see TaskFinishedArgs)
    # The assignment's scheduler epoch (AssignTaskReply.epoch): the
    # cursor above is resume-safe only WITHIN one scheduler incarnation
    # (task_files arrival order is rebuilt on restart) — a stale epoch
    # answers abort, never a file.  "" = pre-epoch peer (served as
    # before; single-incarnation deployments lose nothing).
    epoch: str = ""
    # Who is fetching (quarantine attribution): only the CURRENT
    # assignee's fetches mark the task as demonstrably held — a same-life
    # straggler's fetch must not set the `stamped` evidence that would
    # charge the REASSIGNED worker for a timeout it never caused.
    worker_id: int = -1
    # Peer-to-peer shuffle lost-output report (round 16): the reducer
    # could not fetch this intermediate file — the producing peer is gone
    # (or served a checksum mismatch) after bounded retries AND the
    # daemon relay has no copy.  The scheduler re-enqueues the producing
    # MAP task (its output is gone with the worker — the load-bearing
    # fault path P2P introduces) and this reducer's cursor waits for the
    # re-executed attempt.  "" (elided) on ordinary fetches.
    lost_file: str = ""


@dataclass
class ReduceNextFileReply:
    next_file: str = ""
    done: bool = False
    # The attempt must be ABANDONED (no commit, no finished RPC): its
    # shuffle cursor belongs to a previous scheduler incarnation.
    # Elided when False — old peers interop.
    abort: bool = False
    # Peer-to-peer shuffle (round 16): where next_file actually lives.
    # Set when the producing map attempt kept its output on its own
    # worker's spool — the reducer fetches GET <peer_endpoint>/shuffle/
    # <job>/<name> directly (the daemon never touches the bytes) and
    # verifies size + crc32 against these.  All three elide at their
    # defaults (rpc._REPLY_ELIDE): a peer-shuffle-off daemon's replies
    # stay byte-identical to the pre-peer protocol, and old workers only
    # break when actually handed peer-held work.
    peer_endpoint: str = ""
    peer_size: int = 0
    peer_checksum: str = ""


@dataclass
class HeartbeatArgs:
    task_type: str  # "map" | "reduce"
    task_id: int
    job_id: str = ""  # service multiplexing (see TaskFinishedArgs)
    worker_id: int = -1
    # Declared silent-phase window: "expect no further stamps for up to
    # this many seconds" (cold device compile).  0 = plain stamp, which
    # also CLEARS any previously declared grace.
    grace_s: float = 0.0
    # Span-pipeline piggyback (utils/spans.py), elided from the wire when
    # empty: buffered span/event records, a Metrics counters snapshot
    # (bytes_scanned/gbps aggregates for GET /status), and the clock-sync
    # observations (worker wall-clock at send + measured RTT of the
    # previous heartbeat) the coordinator's ClockSync estimates per-worker
    # offsets from.  spans_seq: see TaskFinishedArgs (retry dedup key).
    spans: list[dict] = field(default_factory=list)
    spans_seq: int = -1
    metrics: dict[str, float] | None = None
    sent_at: float = 0.0  # worker wall clock (time.time()) at send; 0 = off
    rtt_s: float = -1.0  # previous heartbeat's round trip; -1 = unknown


@dataclass
class HeartbeatReply:
    ok: bool = True


_TYPES = {
    "AssignTaskArgs": AssignTaskArgs,
    "AssignTaskReply": AssignTaskReply,
    "TaskFinishedArgs": TaskFinishedArgs,
    "TaskFinishedReply": TaskFinishedReply,
    "ReduceNextFileArgs": ReduceNextFileArgs,
    "ReduceNextFileReply": ReduceNextFileReply,
    "HeartbeatArgs": HeartbeatArgs,
    "HeartbeatReply": HeartbeatReply,
}


# Optional piggyback fields elided from serialized messages when they hold
# their defaults: a span-disabled run's payloads stay byte-identical to the
# pre-span protocol, and a new worker talking to an old coordinator (which
# constructs args via cls(**payload) and would choke on unknown keys) only
# fails when the pipeline is actually switched on.
_ELIDE_DEFAULTS: dict[str, Any] = {
    "spans": [], "spans_seq": -1, "metrics": None,
    "sent_at": 0.0, "rtt_s": -1.0, "filenames": [], "retry_after_s": 0.0,
    "epoch": "", "abort": False, "worker_id": -1, "fused": [],
    # service multiplexing riders (runtime/service.py): absent from the
    # wire on single-job coordinators, so pre-service peers interop
    "job_id": "", "application": "",
    # peer-to-peer shuffle riders (round 16, runtime/peer.py): absent
    # everywhere DGREP_PEER_SHUFFLE is off or the commit went relay-style
    "peer_endpoint": "", "peer_parts": None, "lost_file": "",
}

# Reply wire contract, machine-checked by analyze rule `rpc-elide`: every
# *Reply field declares its side.  _REPLY_BASE is the historical asdict
# shape — always on the wire, because old workers' parsers grew up with
# these keys and changing them would alter every existing payload.
# _REPLY_ELIDE fields drop from the payload at their (falsy) defaults, so
# a daemon with the owning feature off answers byte-identical to the
# protocol that predates the field, and old workers (cls(**payload)
# constructors) only break when actually handed the new work.
_REPLY_BASE = ("assignment", "filename", "task_id", "n_reduce",
               "worker_id", "app_options", "task_timeout_s", "ok",
               "next_file", "done")
_REPLY_ELIDE = ("job_id", "application", "filenames", "retry_after_s",
                "epoch", "fused", "abort",
                "peer_endpoint", "peer_size", "peer_checksum")


def reply_to_dict(msg: Any) -> dict:
    d = dataclasses.asdict(msg)
    for k in _REPLY_ELIDE:
        if not d.get(k, True):
            del d[k]
    return d


def to_dict(msg: Any) -> dict:
    d = dataclasses.asdict(msg)
    for k, default in _ELIDE_DEFAULTS.items():
        if k in d and d[k] == default:
            del d[k]
    return d


def from_dict(cls_name: str, payload: dict) -> Any:
    return _TYPES[cls_name](**payload)

"""Distributed control plane: the coordinator as an HTTP server.

The reference serves Go net/rpc over HTTP on :1234 (coordinator.go:184-193)
and moves all bytes through the coordinator host via SSH/SFTP
(coordinator.go:195-265) — a star topology where the coordinator is also the
data hub.  This module keeps that architecture with TPU-era plumbing:

* control plane: the four verbs of rpc.go as JSON-over-HTTP long-poll
  endpoints (POST /rpc/<verb>) — long-polling happens server-side in the
  scheduler's condition variables, not in 10/50 ms sleep loops;
* data plane: plain HTTP GET/PUT of input splits, intermediate files, and
  final outputs (GET/PUT /data/...), replacing SFTP push/pull — workers
  need no shared filesystem and no SSH credentials (the reference uses
  password-auth-equals-username + InsecureIgnoreHostKey,
  coordinator.go:196-202);
* bootstrap: GET /config hands workers the full JobConfig (application spec
  + options), replacing the reference's hand-copied .so files and hardcoded
  constants;
* observability: GET /status returns task states + metrics.

Workers join implicitly by calling AssignTask — no registry, exactly like
the reference (elasticity by protocol shape, SURVEY.md §5).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from dataclasses import asdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from distributed_grep_tpu.runtime import rpc
from distributed_grep_tpu.runtime.journal import TaskJournal
from distributed_grep_tpu.runtime.scheduler import Scheduler
from distributed_grep_tpu.runtime.types import TaskState
from distributed_grep_tpu.utils.config import JobConfig
from distributed_grep_tpu.utils.io import WorkDir, atomic_write, resolve_input_path
from distributed_grep_tpu.utils.logging import get_logger
from distributed_grep_tpu.utils.metrics import Metrics

log = get_logger("http_coordinator")

def long_poll_window_s(config: JobConfig) -> float:
    """Server-side long-poll window, derived from the single rpc_timeout_s
    knob so the client socket timeout (== rpc_timeout_s, http_transport.py)
    always exceeds it: half the client ceiling, bounded to [5s, 30s]."""
    return min(30.0, max(5.0, config.rpc_timeout_s / 2.0))


class CoordinatorServer:
    def __init__(self, config: JobConfig, resume: bool = False):
        self.config = config
        self.workdir = WorkDir(config.work_dir)
        resume_entries = None
        if resume:
            if config.journal:
                resume_entries = TaskJournal.replay(self.workdir.journal_path())
        else:
            self.workdir.clear()
        journal = TaskJournal(self.workdir.journal_path()) if config.journal else None
        # GET /data/input/ may serve exactly the job's input splits — nothing
        # else on the coordinator's filesystem.
        self.input_allowlist = frozenset(config.input_files)
        self.metrics = Metrics()
        self.scheduler = Scheduler(
            files=list(config.input_files),
            n_reduce=config.n_reduce,
            task_timeout_s=config.task_timeout_s,
            sweep_interval_s=config.sweep_interval_s,
            app_options=config.app_options,
            journal=journal,
            resume_entries=resume_entries,
            metrics=self.metrics,
        )
        self._httpd = ThreadingHTTPServer(
            (config.coordinator_host, config.coordinator_port), _make_handler(self)
        )
        self._httpd.daemon_threads = True
        self._serve_thread: threading.Thread | None = None

    # --- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="http-coordinator", daemon=True
        )
        self._serve_thread.start()
        log.info(
            "coordinator serving on %s:%d (%d map tasks, %d reduce tasks)",
            self.config.coordinator_host,
            self.config.coordinator_port,
            len(self.scheduler.map_tasks),
            self.config.n_reduce,
        )

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def wait_done(self, timeout: float | None = None) -> bool:
        return self.scheduler.wait_done(timeout=timeout)

    def shutdown(self, linger_s: float = 2.0) -> None:
        """Give long-polling workers a moment to receive JOB_DONE, then stop."""
        self.scheduler.stop()
        time.sleep(linger_s)
        self._httpd.shutdown()
        self._httpd.server_close()

    # --- RPC dispatch ------------------------------------------------------
    def handle_rpc(self, verb: str, payload: dict) -> dict:
        window = long_poll_window_s(self.config)
        if verb == rpc.Verb.ASSIGN_TASK:
            reply = self.scheduler.assign_task(rpc.AssignTaskArgs(**payload), timeout=window)
        elif verb == rpc.Verb.MAP_FINISHED:
            reply = self.scheduler.map_finished(rpc.TaskFinishedArgs(**payload))
        elif verb == rpc.Verb.REDUCE_FINISHED:
            reply = self.scheduler.reduce_finished(rpc.TaskFinishedArgs(**payload))
        elif verb == rpc.Verb.REDUCE_NEXT_FILE:
            reply = self.scheduler.reduce_next_file(
                rpc.ReduceNextFileArgs(**payload), timeout=window
            )
        else:
            raise KeyError(f"unknown RPC verb: {verb}")
        return asdict(reply)

    def status(self) -> dict:
        s = self.scheduler
        return {
            "done": s.done(),
            "map": {
                "total": len(s.map_tasks),
                "completed": sum(t.state is TaskState.COMPLETED for t in s.map_tasks),
            },
            "reduce": {
                "total": len(s.reduce_tasks),
                "completed": sum(t.state is TaskState.COMPLETED for t in s.reduce_tasks),
            },
            "metrics": self.metrics.snapshot(),
        }


def _make_handler(server: CoordinatorServer):
    workdir = server.workdir

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through our logger, DEBUG only
            log.debug("http: " + fmt, *args)

        def _send_json(self, obj: dict, code: int = 200) -> None:
            body = json.dumps(obj).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_bytes(self, data: bytes, code: int = 200) -> None:
            self.send_response(code)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _read_body(self) -> bytes:
            length = int(self.headers.get("Content-Length", 0))
            return self.rfile.read(length) if length else b""

        # --- POST /rpc/<verb> ---------------------------------------------
        def do_POST(self):
            try:
                if self.path.startswith("/rpc/"):
                    verb = self.path[len("/rpc/") :]
                    payload = json.loads(self._read_body() or b"{}")
                    self._send_json(server.handle_rpc(verb, payload))
                else:
                    self._send_json({"error": "not found"}, 404)
            except BrokenPipeError:
                pass  # client gave up on a long-poll; scheduler state is safe
            except Exception as e:  # noqa: BLE001 — report, don't kill the server
                log.exception("rpc error on %s", self.path)
                try:
                    self._send_json({"error": str(e)}, 500)
                except OSError:
                    pass

        # --- GET /config /status /data/... --------------------------------
        def do_GET(self):
            try:
                if self.path == "/config":
                    self._send_json(json.loads(server.config.to_json()))
                elif self.path == "/status":
                    self._send_json(server.status())
                elif self.path.startswith("/data/input/"):
                    fname = urllib.parse.unquote(self.path[len("/data/input/") :])
                    if fname not in server.input_allowlist:
                        # Never serve arbitrary coordinator-host files — only
                        # the job's own input splits.
                        self._send_json({"error": f"not an input split: {fname}"}, 403)
                        return
                    try:
                        data = resolve_input_path(fname, workdir).read_bytes()
                    except FileNotFoundError:
                        self._send_json({"error": f"no such input: {fname}"}, 404)
                        return
                    self._send_bytes(data)
                elif self.path.startswith("/data/intermediate/"):
                    name = _safe_name(self.path[len("/data/intermediate/") :])
                    p = workdir.root / "intermediate" / name
                    if not p.exists():
                        self._send_json({"error": f"no such file: {name}"}, 404)
                        return
                    self._send_bytes(p.read_bytes())
                else:
                    self._send_json({"error": "not found"}, 404)
            except BrokenPipeError:
                pass
            except Exception as e:  # noqa: BLE001
                log.exception("get error on %s", self.path)
                try:
                    self._send_json({"error": str(e)}, 500)
                except OSError:
                    pass

        # --- PUT /data/intermediate/<name>, /data/out/<name> --------------
        def do_PUT(self):
            try:
                data = self._read_body()
                if self.path.startswith("/data/intermediate/"):
                    name = _safe_name(self.path[len("/data/intermediate/") :])
                    atomic_write(workdir.root / "intermediate" / name, data)
                    self._send_json({"ok": True})
                elif self.path.startswith("/data/out/"):
                    name = _safe_name(self.path[len("/data/out/") :])
                    atomic_write(workdir.root / "out" / name, data)
                    self._send_json({"ok": True})
                else:
                    self._send_json({"error": "not found"}, 404)
            except Exception as e:  # noqa: BLE001
                log.exception("put error on %s", self.path)
                try:
                    self._send_json({"error": str(e)}, 500)
                except OSError:
                    pass

    return Handler


def _safe_name(name: str) -> str:
    name = urllib.parse.unquote(name)
    if "/" in name or name.startswith("."):
        raise ValueError(f"invalid data-plane file name: {name!r}")
    return name


def serve_coordinator(config: JobConfig, resume: bool = False) -> dict:
    """Blocking entry point for the CLI: serve until the job completes,
    print output file list + metrics, then shut down."""
    server = CoordinatorServer(config, resume=resume)
    server.start()
    server.wait_done()
    status = server.status()
    log.info("job complete: %s", json.dumps(status["metrics"].get("counters", {})))
    server.shutdown()
    print(json.dumps({"outputs": [str(p) for p in server.workdir.list_outputs()]}))
    return status

"""Distributed control plane: the coordinator as an HTTP server.

The reference serves Go net/rpc over HTTP on :1234 (coordinator.go:184-193)
and moves all bytes through the coordinator host via SSH/SFTP
(coordinator.go:195-265) — a star topology where the coordinator is also the
data hub.  This module keeps that architecture with TPU-era plumbing:

* control plane: the four verbs of rpc.go as JSON-over-HTTP long-poll
  endpoints (POST /rpc/<verb>) — long-polling happens server-side in the
  scheduler's condition variables, not in 10/50 ms sleep loops;
* data plane: plain HTTP GET/PUT of input splits, intermediate files, and
  final outputs (GET/PUT /data/...), replacing SFTP push/pull — workers
  need no shared filesystem and no SSH credentials (the reference uses
  password-auth-equals-username + InsecureIgnoreHostKey,
  coordinator.go:196-202);
* bootstrap: GET /config hands workers the full JobConfig (application spec
  + options), replacing the reference's hand-copied .so files and hardcoded
  constants;
* observability: GET /status returns task states + metrics.

Workers join implicitly by calling AssignTask — no registry, exactly like
the reference (elasticity by protocol shape, SURVEY.md §5).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from distributed_grep_tpu.runtime import rpc
from distributed_grep_tpu.runtime.journal import TaskJournal
from distributed_grep_tpu.runtime.scheduler import Scheduler
from distributed_grep_tpu.runtime.store import make_store
from distributed_grep_tpu.runtime.types import TaskState
from distributed_grep_tpu.utils.config import JobConfig
from distributed_grep_tpu.utils import metrics as metrics_mod
from distributed_grep_tpu.utils import spans as spans_mod
from distributed_grep_tpu.utils.io import WorkDir, resolve_input_path
from distributed_grep_tpu.utils.logging import get_logger
from distributed_grep_tpu.utils.metrics import Metrics

log = get_logger("http_coordinator")

# Data-plane block size: GET responses stream from disk and PUT bodies
# stream to disk in blocks of this many bytes, so no split, intermediate
# file, or output ever materializes in coordinator memory (the reference
# whole-file io.Copy's through SFTP, coordinator.go:222-265 — but buffers
# fit Raspberry-Pi-sized files only).  Tests shrink this to prove flow.
BLOCK_BYTES = 1 << 20

def long_poll_window_s(config: JobConfig) -> float:
    """Server-side long-poll window, derived from the single rpc_timeout_s
    knob so the client socket timeout (== rpc_timeout_s, http_transport.py)
    always exceeds it: half the client ceiling, bounded to [5s, 30s]."""
    return min(30.0, max(5.0, config.rpc_timeout_s / 2.0))


class CoordinatorServer:
    def __init__(self, config: JobConfig, resume: bool = False):
        self.config = config
        self.store = make_store(config.store)
        self.workdir = WorkDir(config.work_dir, store=self.store)
        resume_entries = None
        if resume:
            if config.journal:
                resume_entries = TaskJournal.replay(self.workdir.journal_path())
        else:
            self.workdir.clear()
        journal = TaskJournal(self.workdir.journal_path()) if config.journal else None
        # GET /data/input/ may serve exactly the job's input splits — nothing
        # else on the coordinator's filesystem.
        self.input_allowlist = frozenset(config.input_files)
        self.metrics = Metrics()
        # Span pipeline (utils/spans.py): when on, worker-shipped spans and
        # the scheduler's own decisions persist as events.jsonl in the work
        # dir (resume appends — one job, one log across restarts).
        self.event_log = (
            spans_mod.EventLog(
                self.workdir.root / spans_mod.EventLog.FILENAME,
                fresh=not resume,
            )
            if spans_mod.enabled(config.spans) else None
        )
        from distributed_grep_tpu.runtime.job import plan_map_splits

        self.scheduler = Scheduler(
            # batched multi-file splits (cross-file device batching): the
            # member files stay in input_allowlist, so the data plane
            # serves them individually like any other split
            files=plan_map_splits(
                list(config.input_files), config.effective_batch_bytes()
            ),
            n_reduce=config.n_reduce,
            task_timeout_s=config.task_timeout_s,
            sweep_interval_s=config.sweep_interval_s,
            app_options=config.effective_app_options(),
            journal=journal,
            resume_entries=resume_entries,
            metrics=self.metrics,
            commit_resolver=self.workdir.resolve_task_commit,
            event_log=self.event_log,
        )
        self._httpd = ThreadingHTTPServer(
            (config.coordinator_host, config.coordinator_port), _make_handler(self)
        )
        self._httpd.daemon_threads = True
        self._serve_thread: threading.Thread | None = None

    # --- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="http-coordinator", daemon=True
        )
        self._serve_thread.start()
        log.info(
            "coordinator serving on %s:%d (%d map tasks, %d reduce tasks)",
            self.config.coordinator_host,
            self.port,  # the BOUND port (differs from config when it is 0)
            len(self.scheduler.map_tasks),
            self.config.n_reduce,
        )

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def wait_done(self, timeout: float | None = None) -> bool:
        return self.scheduler.wait_done(timeout=timeout)

    def shutdown(self, linger_s: float = 2.0) -> None:
        """Give long-polling workers a moment to receive JOB_DONE, then stop."""
        self.scheduler.stop()
        time.sleep(linger_s)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self.event_log is not None:
            self.event_log.close()

    # --- RPC dispatch ------------------------------------------------------
    def handle_rpc(self, verb: str, payload: dict) -> dict:
        window = long_poll_window_s(self.config)
        if verb == rpc.Verb.ASSIGN_TASK:
            reply = self.scheduler.assign_task(rpc.AssignTaskArgs(**payload), timeout=window)
        elif verb == rpc.Verb.MAP_FINISHED:
            reply = self.scheduler.map_finished(rpc.TaskFinishedArgs(**payload))
        elif verb == rpc.Verb.REDUCE_FINISHED:
            reply = self.scheduler.reduce_finished(rpc.TaskFinishedArgs(**payload))
        elif verb == rpc.Verb.REDUCE_NEXT_FILE:
            reply = self.scheduler.reduce_next_file(
                rpc.ReduceNextFileArgs(**payload), timeout=window
            )
        elif verb == rpc.Verb.HEARTBEAT:
            args = rpc.HeartbeatArgs(**payload)
            self.scheduler.heartbeat(
                args.task_type, args.task_id, grace_s=args.grace_s, args=args
            )
            reply = rpc.HeartbeatReply()
        else:
            raise KeyError(f"unknown RPC verb: {verb}")
        # historical asdict shape, NEW reply fields elided at defaults
        # (rpc.reply_to_dict) — payloads stay byte-identical pre-fusion
        return rpc.reply_to_dict(reply)

    def status(self) -> dict:
        s = self.scheduler
        return {
            "done": s.done(),
            "map": {
                "total": len(s.map_tasks),
                "completed": sum(t.state is TaskState.COMPLETED for t in s.map_tasks),
            },
            "reduce": {
                "total": len(s.reduce_tasks),
                "completed": sum(t.state is TaskState.COMPLETED for t in s.reduce_tasks),
            },
            "metrics": self.metrics.snapshot(),
            # per-worker liveness + heartbeat-shipped Metrics aggregates
            # (bytes_scanned/gbps per worker when the span pipeline is on;
            # liveness alone otherwise), and every in-flight task's
            # heartbeat age / grace window — stragglers visible before the
            # timeout sweeper fires.
            "workers": s.worker_status(),
            "in_flight": s.inflight_status(),
        }


class DataPlaneHandler(BaseHTTPRequestHandler):
    """Shared HTTP plumbing for the one-shot coordinator and the service
    daemon (runtime/service.py): JSON replies, block-streamed file GET with
    prefix-Range resume, store-routed PUT bodies, bounded body drain, and
    the per-task commit-record PUT.  Subclasses own routing (do_GET/PUT/
    POST) and supply the store/work-dir context per request."""

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route through our logger, DEBUG only
        log.debug("http: " + fmt, *args)

    def _send_json(self, obj: dict, code: int = 200) -> None:
        body = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, text: str, code: int = 200) -> None:
        """Plain-text reply — the Prometheus exposition content type
        (GET /metrics on the coordinator and the service daemon)."""
        body = text.encode("utf-8", "strict")
        self.send_response(code)
        self.send_header(
            "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
        )
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_file(self, path) -> None:
        """Stream a file in BLOCK_BYTES chunks; honors a single
        'Range: bytes=N-' prefix range (206 + Content-Range) so a
        worker whose download died mid-body can resume instead of
        refetching the whole split."""
        import shutil

        size = path.stat().st_size
        start = 0
        rng = self.headers.get("Range")
        if rng and rng.startswith("bytes="):
            spec = rng[len("bytes="):].split(",")[0].strip()
            lo, _, hi = spec.partition("-")
            if lo.isdigit() and (not hi or hi.isdigit()):
                start = int(lo)
                # open-ended or to-EOF prefix ranges only, and only
                # inside the file; anything else (incl. start >= size —
                # a 206 with 'bytes N-(N-1)' would be malformed) falls
                # back to a full 200, which the client handles by
                # restarting its spool
                if start >= size or (hi and int(hi) != size - 1):
                    start = 0
        with open(path, "rb") as f:
            f.seek(start)
            if start:
                self.send_response(206)
                self.send_header("Content-Range", f"bytes {start}-{size-1}/{size}")
            else:
                self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(size - start))
            self.end_headers()
            # headers are out: from here a failure must NOT write a JSON
            # error into the half-sent body (the client's Range resume
            # would silently splice those bytes into file content)
            self._streaming_body = True
            shutil.copyfileobj(f, self.wfile, BLOCK_BYTES)

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(length) if length else b""

    def _receive_file(self, store, dst) -> None:
        """Stream the PUT body straight through the work dir's store
        commit protocol (temp+rename on posix, part+record on
        non-atomic) — the body never materializes in coordinator
        memory."""
        length = int(self.headers.get("Content-Length", 0))
        store.put_from_stream(dst, self.rfile, length, BLOCK_BYTES)

    def _drain_body(self) -> None:
        """Discard a request body in bounded blocks (404 paths must not
        buffer a multi-GB body just to answer)."""
        remaining = int(self.headers.get("Content-Length", 0))
        while remaining > 0:
            block = self.rfile.read(min(BLOCK_BYTES, remaining))
            if not block:
                break
            remaining -= len(block)

    def _put_commit(self, store, commits_dir, name: str) -> None:
        """Per-task commit record publication (runtime/store.py): name is
        "<kind>-<task_id>.<attempt>", body the payload.  Sends the HTTP
        reply itself (shared by the coordinator and service routes)."""
        kind_tid, _, attempt = name.partition(".")
        kind, _, tid = kind_tid.rpartition("-")
        if kind not in ("map", "reduce") or not tid.isdigit() or not attempt:
            self._drain_body()
            self._send_json({"error": f"bad commit name: {name}"}, 400)
            return
        if int(self.headers.get("Content-Length", 0)) > 1 << 20:
            self._drain_body()
            self._send_json({"error": "commit record too large"}, 413)
            return
        body = self._read_body()
        store.commit_task(
            commits_dir, kind, int(tid), attempt, json.loads(body or b"{}"),
        )
        self._send_json({"ok": True})


def _make_handler(server: CoordinatorServer):
    workdir = server.workdir

    class Handler(DataPlaneHandler):
        # --- POST /rpc/<verb> ---------------------------------------------
        def do_POST(self):
            try:
                if self.path.startswith("/rpc/"):
                    verb = self.path[len("/rpc/") :]
                    payload = json.loads(self._read_body() or b"{}")
                    self._send_json(server.handle_rpc(verb, payload))
                else:
                    self._send_json({"error": "not found"}, 404)
            except BrokenPipeError:
                pass  # client gave up on a long-poll; scheduler state is safe
            except Exception as e:  # noqa: BLE001 — report, don't kill the server
                log.exception("rpc error on %s", self.path)
                try:
                    self._send_json({"error": str(e)}, 500)
                except OSError:
                    pass

        # --- GET /config /status /data/... --------------------------------
        def do_GET(self):
            self._streaming_body = False  # per request (keep-alive reuses us)
            try:
                if self.path == "/config":
                    self._send_json(json.loads(server.config.to_json()))
                elif self.path == "/status":
                    self._send_json(server.status())
                elif self.path == "/metrics":
                    # Prometheus text exposition of this process's typed
                    # instruments (utils/metrics.py round 15): scheduler
                    # assign-poll/phase histograms + in-process worker
                    # task walls — the one-shot coordinator's scrape
                    # surface (the service daemon adds scale gauges)
                    self._send_text(metrics_mod.render_prometheus())
                elif self.path.startswith("/data/input/"):
                    fname = urllib.parse.unquote(self.path[len("/data/input/") :])
                    if fname not in server.input_allowlist:
                        # Never serve arbitrary coordinator-host files — only
                        # the job's own input splits.
                        self._send_json({"error": f"not an input split: {fname}"}, 403)
                        return
                    p = resolve_input_path(fname, workdir)
                    if not p.exists():
                        self._send_json({"error": f"no such input: {fname}"}, 404)
                        return
                    self._send_file(p)
                elif self.path.startswith("/data/intermediate/"):
                    name = _safe_name(self.path[len("/data/intermediate/") :])
                    # resolve through the store: on a non-atomic store the
                    # logical name maps to the winning committed attempt —
                    # a torn or uncommitted part is never served
                    p = server.store.resolve(workdir.root / "intermediate" / name)
                    if p is None:
                        self._send_json({"error": f"no such file: {name}"}, 404)
                        return
                    self._send_file(p)
                else:
                    self._send_json({"error": "not found"}, 404)
            except BrokenPipeError:
                self.close_connection = True
            except Exception as e:  # noqa: BLE001
                # a failure mid-stream leaves the connection unusable for
                # keep-alive; the client's IncompleteRead triggers its retry
                self.close_connection = True
                log.exception("get error on %s", self.path)
                if getattr(self, "_streaming_body", False):
                    # response headers already sent: writing a JSON error
                    # now would masquerade as body bytes and a Range resume
                    # would commit them as file content — just drop the
                    # connection (short body -> client retries)
                    return
                try:
                    self._send_json({"error": str(e)}, 500)
                except OSError:
                    pass

        # --- PUT /data/intermediate/<name>, /data/out/<name> --------------
        def do_PUT(self):
            try:
                if self.path.startswith("/data/intermediate/"):
                    name = _safe_name(self.path[len("/data/intermediate/") :])
                    self._receive_file(server.store, workdir.root / "intermediate" / name)
                    self._send_json({"ok": True})
                elif self.path.startswith("/data/out/"):
                    name = _safe_name(self.path[len("/data/out/") :])
                    self._receive_file(server.store, workdir.root / "out" / name)
                    self._send_json({"ok": True})
                elif self.path.startswith("/data/commit/"):
                    name = _safe_name(self.path[len("/data/commit/") :])
                    self._put_commit(server.store, workdir.commits_dir(), name)
                else:
                    self._drain_body()  # bounded drain so the 404 gets through
                    self._send_json({"error": "not found"}, 404)
            except Exception as e:  # noqa: BLE001
                # a partially-consumed body pollutes the connection for
                # keep-alive — force a close.  The client surfaces the 500
                # as a failed task attempt; the scheduler's task-timeout
                # re-enqueue is what retries the work.
                self.close_connection = True
                log.exception("put error on %s", self.path)
                try:
                    self._send_json({"error": str(e)}, 500)
                except OSError:
                    pass

    return Handler


def _safe_name(name: str) -> str:
    name = urllib.parse.unquote(name)
    if "/" in name or name.startswith("."):
        raise ValueError(f"invalid data-plane file name: {name!r}")
    return name


def serve_coordinator(config: JobConfig, resume: bool = False) -> dict:
    """Blocking entry point for the CLI: serve until the job completes,
    then shut down.  Returns the final /status dict plus the committed
    output paths under "outputs" — the CLI (cmd_coordinator) owns the
    stdout contract of printing them as one JSON line."""
    server = CoordinatorServer(config, resume=resume)
    server.start()
    server.wait_done()
    status = server.status()
    # The full metrics snapshot — counters AND per-phase timings AND the
    # computed gbps() headline (0.0 here when workers are remote processes:
    # their scan counters live in status["workers"], shipped via heartbeat
    # piggyback) — not just the counters dict the old completion line kept.
    log.info(
        "job complete: %s",
        json.dumps({
            **status["metrics"],
            "throughput_GBps": round(server.metrics.gbps(), 3),
            "workers": status["workers"],
        }, sort_keys=True),
    )
    server.shutdown()
    status["outputs"] = [str(p) for p in server.workdir.list_outputs()]
    return status

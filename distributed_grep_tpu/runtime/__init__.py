"""MapReduce runtime: coordinator scheduling, worker loop, transports.

The runtime reproduces the reference's semantics (map_reduce/coordinator.go,
map_reduce/worker.go) with TPU-era machinery: condition variables instead of
10ms/50ms/1s busy-poll loops, an HTTP long-poll control plane instead of Go
net/rpc, a shared-FS/HTTP data plane instead of SSH+SFTP, and a durable task
journal so a restarted coordinator skips completed work.
"""

from distributed_grep_tpu.runtime.scheduler import Scheduler
from distributed_grep_tpu.runtime.worker import WorkerLoop
from distributed_grep_tpu.runtime.job import run_job

__all__ = ["Scheduler", "WorkerLoop", "run_job"]

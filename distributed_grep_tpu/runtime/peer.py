"""Peer-to-peer shuffle data plane (round 16): the worker data server.

The reference ships every intermediate byte through the coordinator over
SFTP (map_reduce/coordinator.go:316-327), and that star topology survived
in our HTTP data plane: map output PUTs to the daemon, reducers GET it
back — every shuffle byte transits the coordinator NIC twice.  Classic
MapReduce's answer is the one the paper's lab-scale version skipped:
reducers read map output DIRECTLY from the mapper that produced it, the
coordinator keeping only metadata (who holds which partition) and
re-executing map tasks whose output died with a worker.

``PeerDataServer`` is the serving half: a lightweight HTTP server (the
``DataPlaneHandler`` plumbing the workers already run the client half of)
over a local map-output spool.  The worker's map commit writes
``mr-<tid>-<r>`` into the spool (atomic tmp+rename, crc32 self-checksum —
the NonAtomicStore record shape) and registers metadata on the commit
record / TaskFinished RPC; reducers fetch ``GET /shuffle/<job>/<name>``
through the transport retry helpers and verify the checksum.

Loss model: the spool is PROCESS state — a dead worker takes its shuffle
output with it.  That is the deliberate trade (the daemon never touches
the bytes); the scheduler's lost-output path (reducer reports the failed
fetch, the producing MAP task re-enqueues, quarantine charges the
vanished producer) is the load-bearing recovery, proven in the chaos
matrix.

Kill-switch ``DGREP_PEER_SHUFFLE`` (default ON for workers attached to a
service daemon, peer shuffle does not apply to one-shot coordinators):
off is a TRUE no-op — no server starts, no spool exists, every wire
payload stays byte-identical to the pre-peer protocol (the
``DGREP_SERVICE_FUSE=0`` contract).
"""

from __future__ import annotations

import os
import shutil
import threading
import time
import urllib.parse
import zlib
from http.server import ThreadingHTTPServer
from pathlib import Path

from distributed_grep_tpu.runtime.http_coordinator import DataPlaneHandler
from distributed_grep_tpu.utils.logging import get_logger

log = get_logger("peer")

# Spool entries for jobs untouched this long are pruned opportunistically
# on the next put(): the worker never learns job completion (it serves a
# stream of jobs), so age is the bound.  A pruned-but-still-wanted file is
# a clean lost-output report — the map re-executes; it cannot be wrong.
_SPOOL_PRUNE_S = 3600.0


def env_peer_shuffle(default: bool = True) -> bool:
    """Peer-to-peer shuffle switch — the ONE parser of DGREP_PEER_SHUFFLE.
    On (the default for service-attached workers), map output stays on
    the producing worker's spool and reducers fetch it directly;
    "0"/"false"/"no" reverts to the relay data plane exactly (TRUE
    no-op: no server, no spool, byte-identical wire payloads)."""
    raw = os.environ.get("DGREP_PEER_SHUFFLE")
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in ("0", "false", "no")


def env_peer_port(default: int = 0) -> int:
    """Worker data-server listen port — the ONE parser of DGREP_PEER_PORT
    (0 = ephemeral, the default: N worker processes per host each bind
    their own; malformed or negative keeps the default)."""
    raw = os.environ.get("DGREP_PEER_PORT")
    if raw is None or raw == "":
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v >= 0 else default


def env_peer_host(default: str = "") -> str:
    """Advertised shuffle-endpoint host — the ONE parser of
    DGREP_PEER_HOST.  Empty (default) advertises the bind host; set it
    when workers bind a wildcard/NAT'd interface and peers must dial a
    routable name instead."""
    raw = os.environ.get("DGREP_PEER_HOST")
    return raw.strip() if raw else default


def env_peer_bind(default: str = "") -> str:
    """Data-server BIND address — the ONE parser of DGREP_PEER_BIND.
    Empty (the default) binds loopback, UNLESS DGREP_PEER_HOST
    advertises a routable name: an endpoint other hosts are told to
    dial while the server listens on 127.0.0.1 can never connect, so
    the advertise override implies a wildcard bind.  Set both for a
    specific-interface bind behind NAT."""
    raw = os.environ.get("DGREP_PEER_BIND")
    if raw and raw.strip():
        return raw.strip()
    if default:
        return default
    return "0.0.0.0" if env_peer_host() else "127.0.0.1"


def checksum(data: bytes) -> str:
    """The peer-shuffle content self-checksum: crc32 as 8 hex digits —
    the store record format's checksum (runtime/store.encode_record),
    reused so one corruption story covers both commit paths."""
    return f"{zlib.crc32(data):08x}"


def _safe_segment(name: str) -> str:
    name = urllib.parse.unquote(name)
    if "/" in name or name.startswith("."):
        raise ValueError(f"invalid shuffle path segment: {name!r}")
    return name


class PeerDataServer:
    """One worker process's shuffle data server: a local spool of
    committed map output plus an HTTP GET surface other workers' reducers
    fetch from.  Shared by every task-loop slot of the process (names are
    unique per (job, task, partition), so slots never collide)."""

    def __init__(self, host: str | None = None, port: int | None = None,
                 spool_dir: str | None = None):
        import tempfile

        self.spool_root = Path(
            spool_dir or tempfile.mkdtemp(prefix="dgrep-peer-")
        )
        self._owns_spool = spool_dir is None
        host = env_peer_bind() if host is None else host
        self._httpd = ThreadingHTTPServer(
            (host, env_peer_port() if port is None else port),
            _make_peer_handler(self),
        )
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None
        self._closed = False
        adv_host = env_peer_host() or host
        if adv_host in ("0.0.0.0", "::"):
            # explicit wildcard bind with no advertise override: a
            # wildcard is not dialable — fall back to the host's name
            import socket

            adv_host = socket.gethostname()
        self.endpoint = f"http://{adv_host}:{self._httpd.server_address[1]}"
        # Live spool footprint: plain int updated under the GIL (a
        # telemetry counter, not a synchronization primitive — the
        # retry_count convention).
        self._spool_bytes = 0
        self._last_prune = time.monotonic()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "PeerDataServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="peer-data", daemon=True
        )
        self._thread.start()
        log.info("peer shuffle data server serving on %s (spool %s)",
                 self.endpoint, self.spool_root)
        return self

    # ----------------------------------------------------------- spool
    def spool_path(self, job_id: str, name: str) -> Path:
        return (self.spool_root / _safe_segment(job_id or "_")
                / _safe_segment(name))

    def put(self, job_id: str, name: str, data: bytes) -> tuple[int, str]:
        """Commit one intermediate file into the spool (tmp + fsync-free
        rename: a torn spool entry after a crash is indistinguishable
        from a dead worker, and the lost-output path recovers both).
        Returns (size, crc32-hex) — the metadata the commit record and
        the TaskFinished RPC register with the scheduler."""
        p = self.spool_path(job_id, name)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_name(p.name + ".tmp")
        prev = p.stat().st_size if p.exists() else 0
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)
        self._spool_bytes += len(data) - prev
        self._maybe_prune()
        return len(data), checksum(data)

    def get_local(self, job_id: str, name: str) -> bytes:
        """Serve a spool entry without HTTP — the reducer-is-the-producer
        fast path (a worker fetching its own endpoint)."""
        return self.spool_path(job_id, name).read_bytes()

    def spool_bytes(self) -> int:
        return max(0, self._spool_bytes)

    def _maybe_prune(self, max_age_s: float = _SPOOL_PRUNE_S) -> None:
        """Drop job spool dirs untouched for max_age_s (the worker never
        learns job completion).  Opportunistic, at most once per minute;
        a racing fetch of a pruned entry is a clean lost-output report."""
        now = time.monotonic()
        if now - self._last_prune < 60.0:
            return
        self._last_prune = now
        cutoff = time.time() - max_age_s
        try:
            for d in self.spool_root.iterdir():
                if not d.is_dir():
                    continue
                try:
                    if d.stat().st_mtime < cutoff and not any(
                        f.stat().st_mtime >= cutoff for f in d.iterdir()
                    ):
                        freed = sum(
                            f.stat().st_size for f in d.iterdir()
                            if f.is_file()
                        )
                        shutil.rmtree(d, ignore_errors=True)
                        self._spool_bytes -= freed
                        log.info("pruned idle shuffle spool %s (%d bytes)",
                                 d.name, freed)
                except OSError:
                    continue
        except OSError:
            pass

    def close(self) -> None:
        """Stop serving and (when the spool was ours) delete it.  Spool
        entries still wanted by reducers become lost-output reports —
        closing a peer server IS the producer-death event."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            # shutdown() handshakes with serve_forever — calling it on a
            # never-started server blocks forever
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._owns_spool:
            shutil.rmtree(self.spool_root, ignore_errors=True)


def _make_peer_handler(server: PeerDataServer):
    class Handler(DataPlaneHandler):
        # --- GET /shuffle/<job>/<name>, /healthz -----------------------
        def do_GET(self):
            self._streaming_body = False  # per request (keep-alive)
            try:
                if self.path == "/healthz":
                    self._send_json({
                        "ok": True,
                        "spool_bytes": server.spool_bytes(),
                    })
                    return
                if not self.path.startswith("/shuffle/"):
                    self._send_json({"error": "not found"}, 404)
                    return
                rest = self.path[len("/shuffle/"):]
                parts = rest.split("/", 1)
                if len(parts) != 2:
                    self._send_json(
                        {"error": f"bad shuffle path: {self.path!r}"}, 400)
                    return
                p = server.spool_path(parts[0], parts[1])
                if not p.exists():
                    # gone (pruned / never produced here): the reducer's
                    # declared-failure path reports it lost and the map
                    # re-executes — answer honestly, never hang
                    self._send_json({"error": f"no such file: {rest}"}, 404)
                    return
                self._send_file(p)
            except BrokenPipeError:
                self.close_connection = True
            except Exception as e:  # noqa: BLE001 — report, don't kill serving
                self.close_connection = True
                log.exception("peer get error on %s", self.path)
                if getattr(self, "_streaming_body", False):
                    return  # headers out: never splice JSON into a body
                try:
                    self._send_json({"error": str(e)}, 500)
                except OSError:
                    pass

    return Handler

"""Worker task loop — mirrors map_reduce/worker.go:126-178.

Loop: ask for work (long-poll AssignTask); on a map assignment read the
split, run the application's map, bucketize by FNV-32a partition, commit
intermediate files atomically, notify MapFinished; on a reduce assignment
stream intermediate files one at a time via ReduceNextFile (the pipelined
shuffle — reduce starts while maps still run), sort-merge group, run the
application's reduce per distinct key, commit the output atomically, notify
ReduceFinished.

Differences from the reference, on purpose:
* clean shutdown on an explicit JOB_DONE assignment instead of dying via
  log.Fatal when the coordinator closes connections (worker.go:223);
* app options (grep pattern) arrive with the assignment and are applied via
  the application's configure hook — the plumbing the reference never built;
* a fault-injection hook table for tests (SURVEY.md §5 calls for one);
* reduce output lines are sorted by key for deterministic output (the
  reference iterates a Go map — nondeterministic order, worker.go:163-168).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from distributed_grep_tpu.apps.loader import LoadedApplication
from distributed_grep_tpu.runtime import rpc, shuffle
from distributed_grep_tpu.runtime.extsort import ExternalReducer
from distributed_grep_tpu.runtime.transport import Transport
from distributed_grep_tpu.utils import metrics as metrics_mod
from distributed_grep_tpu.utils import spans as spans_mod
from distributed_grep_tpu.utils import trace
from distributed_grep_tpu.utils.logging import get_logger
from distributed_grep_tpu.utils.metrics import Metrics

log = get_logger("worker")

# Typed task-wall histograms (utils/metrics.py round 15): in-process
# workers land in the daemon's /metrics; remote workers in their own
# process's registry.
_H_MAP_TASK = metrics_mod.histogram("dgrep_map_task_seconds")
_H_REDUCE_TASK = metrics_mod.histogram("dgrep_reduce_task_seconds")


class WorkerKilled(Exception):
    """Raised by fault-injection hooks to simulate a worker crash."""


class TaskAborted(Exception):
    """The coordinator fenced this attempt off (stale scheduler epoch —
    the attempt outlived a coordinator/daemon restart): abandon it with
    NO commit and NO finished RPC, then go back to polling for work."""


def _engine_cache_counters() -> dict | None:
    """This process's cross-job engine-cache counters — compiled-model
    (compile_cache_hits/misses/evictions), device-corpus
    (corpus_cache_hits/misses/evictions/bytes_resident), AND scan-fusion
    (fused_queries/fused_dispatches/fusion_bytes_saved, ops/fuse.py) —
    or None when the owning modules were never imported or none was
    touched; piggybacked with the Metrics snapshot so the coordinator
    /status workers view shows cache/fusion effectiveness per worker.
    sys.modules-gated: a wordcount worker must not import the whole ops
    stack just to report nothing."""
    import sys as _sys

    counters: dict = {}
    eng = _sys.modules.get("distributed_grep_tpu.ops.engine")
    if eng is not None:
        counters.update(eng.model_cache_counters())
    lay = _sys.modules.get("distributed_grep_tpu.ops.layout")
    if lay is not None:
        counters.update(lay.corpus_cache_counters())
    fuse = _sys.modules.get("distributed_grep_tpu.ops.fuse")
    if fuse is not None:
        counters.update(fuse.fusion_counters())
    idx = _sys.modules.get("distributed_grep_tpu.index.summary")
    if idx is not None:
        # shard-index engine-side counters (index_shards_pruned/
        # bytes_skipped/maybe_scans/summaries_built), nonzero-only
        counters.update(idx.index_counters())
    fol = _sys.modules.get("distributed_grep_tpu.runtime.follow")
    if fol is not None:
        # streaming-tier counters (follow_wakes/suffix_bytes_scanned/
        # stream_dropped_records), nonzero-only — same contract
        counters.update(fol.follow_counters())
        # fused follow tier (round 21): follow_fused_* counters
        counters.update(fol.follow_fused_counters())
    return counters or None


class WorkerLoop:
    def __init__(
        self,
        transport: Transport,
        app: LoadedApplication | None = None,
        metrics: Optional[Metrics] = None,
        fault_hooks: Optional[dict[str, Callable[[], None]]] = None,
        reduce_memory_bytes: int = 128 << 20,
        spill_dir: Optional[str] = None,
        spans_enabled: Optional[bool] = None,
        job_id: str = "",
        peer=None,
    ):
        self.transport = transport
        # Peer-to-peer shuffle (round 16, runtime/peer.py): when a
        # PeerDataServer is attached, map commits spool their output
        # LOCALLY and register metadata instead of uploading bytes to the
        # daemon; one server is shared by every slot of the process.
        # None = relay shuffle, the pre-peer data plane exactly.
        self.peer = peer
        # Elastic shrink signal (dgrep serve --max-workers): set by the
        # service's local-pool scaler; the loop exits at the next idle
        # moment (never mid-task — attach/detach safety is the round-8
        # fresh-id/quarantine machinery).
        self.drain = threading.Event()
        # ``app`` may be None for workers attached to the service daemon
        # (runtime/service.py): there every assignment names its own
        # application module (AssignTaskReply.application) and the loop
        # resolves it per task (_bind_assignment) — one fresh module
        # instance per (loop, spec), cached, so two loops never share
        # app-module state and a loop reuses its instance across jobs.
        self.app = app
        self._job_apps: dict[str, LoadedApplication] = {}
        # The SERVICE job id of the current assignment, echoed on every
        # task RPC so the daemon can dispatch to the right scheduler.
        # Stays "" on single-job coordinators: the rpc fields elide and
        # the wire payload is byte-identical to the pre-service protocol.
        self._rpc_job_id = ""
        self.metrics = metrics or Metrics()
        self.fault_hooks = fault_hooks or {}
        self.reduce_memory_bytes = reduce_memory_bytes
        # Spills must land on real disk: the system temp dir is often a
        # RAM-backed tmpfs, which would defeat the reduce memory cap.
        self.spill_dir = spill_dir
        self.worker_id = -1
        # Span pipeline (utils/spans.py): None defers to the DGREP_SPANS
        # env var; run_job/run_http_worker pass JobConfig.spans explicitly.
        # Off means NO buffer exists — every emit site no-ops and RPC
        # payloads keep their pre-span shape (rpc._ELIDE_DEFAULTS).
        if spans_enabled is None:
            spans_enabled = spans_mod.env_enabled()
        self.spans = spans_mod.SpanBuffer() if spans_enabled else None
        self.job_id = job_id
        self._hb_rtt = -1.0  # last heartbeat round trip (ClockSync feed)
        self._assign_wait_s = 0.0

    def _fault(self, point: str) -> None:
        hook = self.fault_hooks.get(point)
        if hook:
            hook()

    def _attach_rpc_retries(self, args) -> None:
        """Piggyback the transport's transient-retry count — UNGATED by
        the span pipeline (an operator debugging a flaky fleet looks for
        rpc_retries in /status precisely when spans are off) but
        nonzero-only, so the zero-retry default keeps the wire payload
        byte-identical to the pre-span protocol."""
        retries = getattr(self.transport, "retry_count", 0)
        if retries:
            if args.metrics is None:
                args.metrics = {}
            args.metrics["rpc_retries"] = retries
        # Peer-shuffle telemetry rides the same ungated-but-nonzero-only
        # contract: an operator watching a fleet drain looks for spool
        # state and fetch-failure counts in /status with spans off, and
        # the zero defaults keep peer-free payloads byte-identical.
        stats: dict[str, float] = {}
        for k in ("peer_fetches", "peer_fetch_failures", "relay_fallbacks"):
            v = self.metrics.counters.get(k, 0)
            if v:
                stats[k] = v
        if self.peer is not None:
            sb = self.peer.spool_bytes()
            if sb:
                stats["peer_spool_bytes"] = float(sb)
        if stats:
            if args.metrics is None:
                args.metrics = {}
            args.metrics.update(stats)

    # --------------------------------------------------------------- liveness
    def _hb_interval(self, window_s: float) -> float:
        """Heartbeat cadence derived from the coordinator's declared
        detector window (AssignTaskReply.task_timeout_s): ~window/3 gives
        two chances to land a stamp per window, bounded to [50 ms, 5 s]."""
        return min(5.0, max(0.05, float(window_s) / 3.0))

    def _heartbeat(self, task_type: str, task_id: int,
                   grace_s: float = 0.0, job_id: str | None = None) -> None:
        """Advisory mid-task stamp (UpdateTimestamp, coordinator.go:176-182
        — exposed by the reference but never called mid-map; here it is
        what lets the sweeper run a tight window over long maps, VERDICT
        r3 item 3).  ``job_id`` overrides the current assignment's job
        for FUSED attempts (one scan holds K jobs' tasks; every
        participant's scheduler must see stamps).  Never raises: liveness
        is best-effort, the task's own RPCs surface real transport
        failure."""
        hb = getattr(self.transport, "heartbeat", None)
        if hb is None:
            return
        args = rpc.HeartbeatArgs(
            task_type=task_type, task_id=task_id,
            job_id=self._rpc_job_id if job_id is None else job_id,
            worker_id=self.worker_id, grace_s=grace_s,
        )
        if self.spans is not None:
            # Piggyback: buffered spans flush on the stamp the worker was
            # sending anyway (a failed stamp loses this batch — telemetry
            # is best-effort by the same contract as the stamp itself);
            # sent_at + the previous round trip feed the coordinator's
            # per-worker clock-offset estimate.
            args.spans_seq, args.spans = self.spans.drain_batch()
            args.metrics = self.metrics.piggyback()
            cc = _engine_cache_counters()
            if cc:
                args.metrics.update(cc)
            # source token for the service-side rolling-rate tracker:
            # same-process loops share module-global cache counters, and
            # a reconnect gets a fresh worker id — the token (not the id)
            # is what keeps deltas counted exactly once per process
            args.metrics["proc"] = metrics_mod.PROC_TOKEN
            args.sent_at = time.time()
            args.rtt_s = self._hb_rtt
        self._attach_rpc_retries(args)
        try:
            rtt = hb(args)
            # Transports that measure return the successful POST's round
            # trip as a float (retry sleeps excluded).  Anything else —
            # None from a stamp that exhausted its attempts, or a custom
            # transport without measurement — is NOT a valid sample: keep
            # the previous value rather than poison the clock sync with
            # timeout+retry wall time (a 16 s "RTT" would skew the
            # worker's whole trace row by seconds).
            if isinstance(rtt, float):
                self._hb_rtt = rtt
        except Exception:  # noqa: BLE001 — advisory by contract
            pass

    def _progress_fn(self, task_type: str, task_id: int,
                     window_s: float = 10.0) -> Callable:
        """A throttled progress callback for the application: plain calls
        stamp at most once per _hb_interval(window); grace calls (declaring
        a silent phase, e.g. a 20-40 s cold device compile) always go
        through."""
        last = [0.0]
        min_interval = self._hb_interval(window_s)

        def progress(grace_s: float = 0.0) -> None:
            now = time.monotonic()
            if not grace_s and now - last[0] < min_interval:
                return
            last[0] = now
            self._heartbeat(task_type, task_id, grace_s=grace_s)

        return progress

    def _pumping(self, task_type: str, task_id: int, interval_s: float = 2.0):
        """Context manager: stamp heartbeats from a side thread while the
        body runs — coarse process-alive liveness.  Two call sites:
        transport downloads (on non-local transports — the HTTP data
        plane has its own 15 s liveness budget, http_transport.py, so no
        app hang can hide there; the local transport resolves in
        microseconds and skips the pump, round 5), and the map COMPUTE
        leg of apps without set_progress
        support (there it genuinely cannot distinguish a slow map from a
        hung one — the accepted tradeoff, documented at the call site,
        because the alternative is spuriously re-executing every map
        longer than the sweep window; progress-capable apps keep
        fine-grained hang detection instead)."""
        import contextlib
        import threading

        @contextlib.contextmanager
        def ctx():
            stop = threading.Event()

            def pump() -> None:
                while not stop.wait(interval_s):
                    self._heartbeat(task_type, task_id)

            t = threading.Thread(target=pump, name="hb-pump", daemon=True)
            t.start()
            try:
                yield
            finally:
                stop.set()
                t.join(timeout=interval_s + 1.0)

        return ctx()

    def run(self) -> None:
        """The infinite task loop (worker.go:126-178), with a clean exit."""
        while True:
            if self.drain.is_set():
                # elastic shrink: exit at an idle loop top, never mid-task
                log.info("worker %d: drained (elastic shrink), exiting",
                         self.worker_id)
                return
            t_wait = time.monotonic()
            args = rpc.AssignTaskArgs(worker_id=self.worker_id)
            if self.peer is not None:
                # advertise the shuffle endpoint on every poll so /status
                # shows who holds spool state before an operator drains
                args.peer_endpoint = self.peer.endpoint
            reply = self.transport.assign_task(args)
            # idle wait for work — reported as an arg on the task span
            self._assign_wait_s = time.monotonic() - t_wait
            self.worker_id = reply.worker_id
            if reply.assignment in (rpc.Assignment.MAP, rpc.Assignment.REDUCE):
                self._bind_assignment(reply)
            if self.spans is not None:
                # buffer-synthesized records (drop reports) render on this
                # worker's row now that the coordinator named it
                self.spans.base_tags.update(
                    job=self.job_id, worker=self.worker_id
                )
            if reply.assignment == rpc.Assignment.JOB_DONE:
                log.info("worker %d: job done, exiting", self.worker_id)
                return
            if reply.assignment == rpc.Assignment.MAP:
                self._run_map(reply)
            elif reply.assignment == rpc.Assignment.REDUCE:
                self._run_reduce(reply)
            elif reply.retry_after_s > 0:
                # quarantined (scheduler.WorkerHealth): the coordinator
                # hinted how long until re-probation — sleep a bounded
                # slice of it instead of re-entering the long-poll hot
                # (capped so a shrunk window server-side is noticed)
                time.sleep(min(reply.retry_after_s, 5.0))
            # anything else ("retry"): long-poll window expired — loop again

    def _bind_assignment(self, reply: rpc.AssignTaskReply) -> None:
        """Adopt a (possibly multiplexed) assignment's job identity: span
        tags + data-plane scope follow the job, and the application module
        resolves from the assignment when the daemon names one (service
        workers serve many jobs through ONE attach).  Single-job replies
        carry neither field and this is a no-op."""
        if reply.job_id:
            self._rpc_job_id = reply.job_id
            self.job_id = reply.job_id
            bind = getattr(self.transport, "bind_job", None)
            if bind is not None:
                bind(reply.job_id)
        if reply.application:
            app = self._job_apps.get(reply.application)
            if app is None:
                from distributed_grep_tpu.apps.loader import load_application

                app = load_application(reply.application)
                self._job_apps[reply.application] = app
            self.app = app
        elif self.app is None:
            raise RuntimeError(
                "worker has no application: the assignment names none and "
                "no default app was given at construction"
            )

    def _publish_commit(self, kind: str, task_id: int, attempt: str,
                        payload: dict) -> None:
        """Publish the per-task commit record (runtime/store.py) — the
        durable commit on stores without atomic rename, published after
        every blob of the task is durable and BEFORE the finished RPC, so
        the record (not the RPC, not raw file existence) is the unit of
        truth the scheduler registers from.  Transports without the hook
        (custom test transports) keep RPC-args registration."""
        publish = getattr(self.transport, "publish_task_commit", None)
        if publish is not None:
            with spans_mod.span(f"{kind}:commit", cat=kind):
                publish(kind, task_id, attempt, payload)

    def _task_ctx(self, kind: str, task_id: int, attempt: str):
        """The span pipeline's ambient task context for one attempt — a
        nullcontext when the pipeline is off, so every emit site below
        no-ops (utils/spans.active)."""
        if self.spans is None:
            import contextlib

            return contextlib.nullcontext()
        return spans_mod.task_context(
            self.spans, job=self.job_id, worker=self.worker_id,
            task=task_id, attempt=attempt, kind=kind,
        )

    def _finished_args(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedArgs:
        """Attach the final span flush + metrics snapshot to a finished
        RPC — the last chance to ship this attempt's telemetry (a worker
        may exit before any further heartbeat), so unlike the heartbeat's
        FLUSH_MAX batches this drains EVERYTHING (bounded by the buffer
        cap + one drop report)."""
        if self.spans is not None:
            args.spans_seq, args.spans = self.spans.drain_batch(
                limit=self.spans.cap + 1
            )
            args.metrics = self.metrics.piggyback()
            cc = _engine_cache_counters()
            if cc:
                args.metrics.update(cc)
            args.metrics["proc"] = metrics_mod.PROC_TOKEN  # see _heartbeat
        self._attach_rpc_retries(args)
        return args

    def _read_members(self, names: list[str], want_paths: bool
                      ) -> tuple[list, int]:
        """Resolve split members to (name, bytes-or-local-path) items +
        total bytes.  ``want_paths`` hands over resolved paths on local
        data planes (the device corpus cache then serves warm windows
        with zero reads); spooled temp copies honor the (path, is_temp)
        contract — read and unlinked, never handed over as a path (a
        transient realpath must not become a corpus content key).
        Shared by the batched map branch and the fused attempt."""
        import os as _os

        items: list = []
        n_bytes = 0
        if (want_paths
                and getattr(self.transport, "is_local", False)
                and hasattr(self.transport, "read_input_path")):
            for name in names:
                p, is_temp = self.transport.read_input_path(name)
                if is_temp:
                    with open(p, "rb") as _fh:
                        data_b = _fh.read()
                    _os.unlink(p)
                    items.append((name, data_b))
                    n_bytes += len(data_b)
                else:
                    items.append((name, str(p)))
                    n_bytes += _os.path.getsize(p)
        else:
            for name in names:
                b = self.transport.read_input(name)
                items.append((name, b))
                n_bytes += len(b)
        return items, n_bytes

    # ------------------------------------------------------------------- map
    def _run_map(self, a: rpc.AssignTaskReply) -> None:
        if a.fused:
            # cross-tenant scan fusion (runtime/fusion.py): this
            # assignment carries co-tenant tasks — one scan, K commits
            self._run_map_fused(a)
            return
        from distributed_grep_tpu.runtime.store import new_attempt_id

        t0 = time.perf_counter()
        t0_wall = time.time()
        attempt = new_attempt_id()
        with self._task_ctx("map", a.task_id, attempt):
            produced, peer_meta = self._map_attempt(a, attempt, t0)
            spans_mod.complete(
                "map:task", t0_wall, time.time() - t0_wall, cat="map",
                assign_wait_s=round(self._assign_wait_s, 6),
            )
            self._fault("before_map_finished")
            finished = rpc.TaskFinishedArgs(
                task_id=a.task_id, job_id=self._rpc_job_id,
                worker_id=self.worker_id,
                produced_parts=produced,
            )
            if peer_meta is not None:
                finished.peer_endpoint = peer_meta["endpoint"]
                finished.peer_parts = peer_meta["parts"]
            self.transport.map_finished(self._finished_args(finished))
        self.metrics.inc("map_tasks")
        self.metrics.observe("map_task_total", time.perf_counter() - t0)
        _H_MAP_TASK.observe(time.perf_counter() - t0)

    def _write_map_outputs(self, task_id: int, buckets: dict
                           ) -> tuple[list[int], dict | None]:
        """Commit one map attempt's partition files and return (produced
        partitions, peer metadata or None).  Peer shuffle active (a
        PeerDataServer attached and a service job bound): the bytes land
        on THIS worker's spool — atomic rename, crc32 self-checksum —
        and only the metadata travels; otherwise the pre-peer transport
        PUT (relay) runs unchanged."""
        produced: list[int] = []
        peer_active = self.peer is not None and bool(self._rpc_job_id)
        parts_meta: dict[str, list] = {}
        for r, kvs in sorted(buckets.items()):
            data = shuffle.encode_records(kvs)
            name = f"mr-{task_id}-{r}"
            if peer_active:
                size, crc = self.peer.put(self._rpc_job_id, name, data)
                parts_meta[str(r)] = [size, crc]
            else:
                # Atomic write == the temp-file + rename commit (worker.go:103).
                self.transport.write_intermediate(name, data)
            produced.append(r)
        if not peer_active:
            return produced, None
        return produced, {
            "endpoint": self.peer.endpoint,
            "worker": self.worker_id,
            "parts": parts_meta,
        }

    def _map_attempt(self, a: rpc.AssignTaskReply, attempt: str,
                     t0: float) -> tuple[list[int], dict | None]:
        self.app.configure(**a.app_options)
        # Streaming boundary: an app exposing map_path_fn receives a local
        # file path and reads it in bounded chunks (engine.scan_file) —
        # splits larger than worker RAM flow end-to-end.  Everyone else
        # gets the reference-shaped whole-bytes map_fn (worker.go:72-76).
        use_path = getattr(self.app, "map_path_fn", None) is not None and hasattr(
            self.transport, "read_input_path"
        )
        # Mid-task liveness (VERDICT r3 item 3): the app's progress callback
        # stamps the coordinator per chunk/segment (throttled), so the
        # failure detector keeps a tight window even over maps that
        # legitimately run long; downloads are covered by the pump thread
        # (they progress against the coordinator's own data plane).  Apps
        # WITHOUT progress support (wordcount over a big split) get the
        # pump over their compute leg too: coarse liveness (process alive)
        # beats the alternative — spurious re-execution of every map
        # longer than the window, forever.  Progress-capable apps rely on
        # their own stamps there, which unlike the pump also catch
        # app-level hangs.
        has_progress = self.app.set_progress(
            self._progress_fn("map", a.task_id, a.task_timeout_s)
        )
        pump_s = min(2.0, self._hb_interval(a.task_timeout_s))
        import contextlib

        def compute_guard():
            if has_progress:
                return contextlib.nullcontext()
            return self._pumping("map", a.task_id, pump_s)

        # Download-leg pumping only matters when the data plane can
        # actually take a while (HTTP pull): a local-filesystem transport
        # resolves the path in microseconds, and a pump thread per map
        # task is measurable overhead on a 2,000-file grep -r (round 5).
        def download_guard():
            if getattr(self.transport, "is_local", False):
                return contextlib.nullcontext()
            return self._pumping("map", a.task_id, pump_s)

        try:
            if a.filenames:
                # Batched multi-file split (cross-file device batching,
                # runtime/job.plan_map_splits): every member is below the
                # small-input threshold by construction, so whole-bytes
                # reads are bounded by the batch window.  Apps exposing
                # map_batch_fn amortize the scan across members (grep_tpu
                # packs them into shared device dispatches); others get
                # map_fn per member — still one task, one commit, one
                # journal entry instead of len(members) of each.
                batch_fn = self.app.map_batch_fn
                # Local data plane + a batch fn that accepts paths
                # (map_batch_paths, grep_tpu): hand over resolved member
                # paths instead of reading them here — the engine's
                # device corpus cache (round 7) then serves a warm
                # window with ZERO file reads, and cold members cost the
                # same whole-read scan_batch would have done anyway.
                batch_paths = (
                    batch_fn is not None
                    and getattr(self.app, "map_batch_paths", False)
                )
                with download_guard(), \
                        trace.annotate(f"map_read:{a.task_id}"), \
                        spans_mod.span("map:read", cat="map",
                                       file=a.filename,
                                       files=len(a.filenames)):
                    blobs, n_bytes = self._read_members(
                        a.filenames, want_paths=batch_paths
                    )
                self._fault("after_map_read")
                with self.metrics.timer("map_compute"), \
                        trace.annotate(f"map_compute:{a.task_id}"), \
                        spans_mod.span("map:compute", cat="map"), \
                        compute_guard():
                    if batch_fn is not None:
                        records = batch_fn(blobs)
                    else:
                        records = [
                            r for name, b in blobs
                            for r in self.app.map_fn(name, b)
                        ]
                self.metrics.record_scan(n_bytes, time.perf_counter() - t0)
            elif use_path:
                import os

                with download_guard(), \
                        trace.annotate(f"map_read:{a.task_id}"), \
                        spans_mod.span("map:read", cat="map", file=a.filename):
                    path, is_temp = self.transport.read_input_path(a.filename)
                try:
                    self._fault("after_map_read")
                    n_bytes = os.path.getsize(path)
                    with self.metrics.timer("map_compute"), \
                            trace.annotate(f"map_compute:{a.task_id}"), \
                            spans_mod.span("map:compute", cat="map"), \
                            compute_guard():
                        records = self.app.map_path_fn(a.filename, str(path))
                finally:
                    if is_temp:
                        os.unlink(path)
                self.metrics.record_scan(n_bytes, time.perf_counter() - t0)
            else:
                with download_guard(), \
                        trace.annotate(f"map_read:{a.task_id}"), \
                        spans_mod.span("map:read", cat="map", file=a.filename):
                    contents = self.transport.read_input(a.filename)
                self._fault("after_map_read")
                with self.metrics.timer("map_compute"), \
                        trace.annotate(f"map_compute:{a.task_id}"), \
                        spans_mod.span("map:compute", cat="map"), \
                        compute_guard():
                    records = self.app.map_fn(a.filename, contents)
                self.metrics.record_scan(len(contents), time.perf_counter() - t0)
        finally:
            if has_progress:
                self.app.set_progress(None)
        # The shuffle leg (bucketize + intermediate writes) is worker-side
        # code with no app involvement, and on a match-dense map it can
        # run past the sweep window by itself (549k records measured ~8 s
        # on this host — observed swept mid-shuffle and re-executed; the
        # round-8 native record build runs HERE too — a DeferredBatch
        # partitions from its source bytes inside bucketize, so the
        # map:shuffle span now carries the one-pass build).  The
        # coarse pump is the right liveness here, same tradeoff as the
        # download legs: a hang in OUR shuffle is a worker bug, not an
        # app hang the detector needs to catch.  Small outputs skip the
        # pump: their shuffle leg is sub-millisecond ON THE LOCAL
        # TRANSPORT (bucketize scales with records; a remote transport's
        # intermediate PUSH can stall on the network at any size, so it
        # always keeps the pump), nowhere near any sweep window — and a
        # thread per map task costs real time on many-small-file jobs
        # (round 5).
        def shuffle_guard():
            if getattr(self.transport, "is_local", False):
                from distributed_grep_tpu.runtime.columnar import LineBatch

                n_records = sum(
                    len(r) if isinstance(r, LineBatch) else 1 for r in records
                )
                if n_records < 50_000:
                    return contextlib.nullcontext()
            return self._pumping("map", a.task_id, pump_s)

        with shuffle_guard(), spans_mod.span("map:shuffle", cat="map"):
            buckets = shuffle.bucketize(records, a.n_reduce)
            self._fault("before_map_commit")
            produced, peer_meta = self._write_map_outputs(a.task_id, buckets)
        payload: dict = {"parts": produced}
        if peer_meta is not None:
            # the commit record carries the peer metadata too — it is
            # the durable copy a restarted daemon re-registers from
            payload["peer"] = peer_meta
        self._publish_commit("map", a.task_id, attempt, payload)
        return produced, peer_meta

    # ------------------------------------------------------------ fused map
    def _run_map_fused(self, a: rpc.AssignTaskReply) -> None:
        """One worker scan serving K co-tenant map tasks (cross-tenant
        scan fusion — runtime/fusion.py planned it, ops/fuse.py runs it).
        The primary assignment's split is read ONCE (the planner matched
        the participants' splits by content identity); the app's
        map_fused_fn produces each participant's records from one union
        scan; each participant then commits through ITS OWN job's data
        plane, commit record, and finished RPC — per-job exactly-once,
        journals, attempt resolution, and the epoch fence are untouched.
        Any failure in the fused leg falls back to per-participant SOLO
        execution over the already-read items (fusion is a fast path,
        never a correctness dependency); a participant whose commit leg
        fails simply times out in its own scheduler and re-runs solo."""
        from distributed_grep_tpu.runtime.store import new_attempt_id

        t0_wall = time.time()
        participants: list[dict] = [{
            "job_id": a.job_id, "task_id": a.task_id,
            "filename": a.filename, "filenames": list(a.filenames),
            "n_reduce": a.n_reduce, "app_options": a.app_options,
            "epoch": a.epoch, "task_timeout_s": a.task_timeout_s,
        }]
        participants += [dict(p) for p in a.fused]
        part_ids = [(p["job_id"], p["task_id"]) for p in participants]

        # Fused liveness: EVERY participant's scheduler must see stamps,
        # or co-tenants' sweepers would re-enqueue tasks this worker is
        # actively scanning.  The throttled callback fans one stamp out
        # to K (job, task) pairs; grace declarations pass through.  The
        # cadence derives from the TIGHTEST participant's declared
        # detector window (fusion_key does not align task_timeout_s — a
        # co-tenant with a 2 s window must not be stamped on the
        # primary's 60 s cadence and swept mid-scan).
        window_s = min(
            float(p.get("task_timeout_s", a.task_timeout_s))
            for p in participants
        )
        min_interval = self._hb_interval(window_s)
        last = [0.0]

        def progress(grace_s: float = 0.0) -> None:
            now = time.monotonic()
            if not grace_s and now - last[0] < min_interval:
                return
            last[0] = now
            for jid_p, tid_p in part_ids:
                self._heartbeat("map", tid_p, grace_s=grace_s, job_id=jid_p)

        import contextlib
        import threading

        def fused_pump(force: bool = False):
            """Coarse liveness over legs with no app progress (download,
            shuffle/commit) — the solo path's download_guard/
            shuffle_guard, fanned out to every participant's (job, task)
            so no co-tenant's sweeper fires mid-leg.  Local transports
            skip it like the solo guards do (reads/writes resolve in
            microseconds there) unless ``force`` (match-dense local
            shuffle legs can outrun the sweep window by themselves —
            the solo shuffle_guard's 50k-record rule)."""
            if not force and getattr(self.transport, "is_local", False):
                return contextlib.nullcontext()

            @contextlib.contextmanager
            def ctx():
                stop = threading.Event()
                interval = min(2.0, min_interval)

                def pump() -> None:
                    while not stop.wait(interval):
                        for jid_p, tid_p in part_ids:
                            self._heartbeat("map", tid_p, job_id=jid_p)

                t = threading.Thread(target=pump, name="fused-hb-pump",
                                     daemon=True)
                t.start()
                try:
                    yield
                finally:
                    stop.set()
                    t.join(timeout=interval + 1.0)

            return ctx()

        names = list(a.filenames) or [a.filename]
        want_paths = bool(getattr(self.app, "map_batch_paths", False))
        attempt0 = new_attempt_id()
        committed = 0
        t0 = time.perf_counter()  # attempt start, like _run_map: the
        # record_scan/map_task_total telemetry must include the read leg
        # or fused gbps reads systematically higher than solo's
        with self._task_ctx("map", a.task_id, attempt0):
            with fused_pump(), \
                    trace.annotate(f"map_read:{a.task_id}"), \
                    spans_mod.span("map:read", cat="map", file=a.filename,
                                   files=len(names)):
                items, n_bytes = self._read_members(names, want_paths)
            self._fault("after_map_read")
            has_progress = self.app.set_progress(progress)
            records_per: list | None = None
            try:
                if self.app.map_fused_fn is not None:
                    with self.metrics.timer("map_compute"), \
                            trace.annotate(f"map_compute:{a.task_id}"), \
                            spans_mod.span("map:compute", cat="map",
                                           fused=len(participants)):
                        records_per = self.app.map_fused_fn(
                            items, participants
                        )
            except Exception:  # noqa: BLE001 — fusion is a fast path only
                log.exception(
                    "fused map attempt failed (%d queries); falling back "
                    "to solo per-participant execution", len(participants),
                )
                records_per = None
            finally:
                if has_progress:
                    self.app.set_progress(None)
            self.metrics.record_scan(n_bytes, time.perf_counter() - t0)

            def dense_records() -> bool:
                # the solo shuffle_guard's 50k-record rule, summed over
                # participants: a local match-dense commit loop can
                # outrun the sweep window with no RPC activity
                if records_per is None:
                    return False
                from distributed_grep_tpu.runtime.columnar import LineBatch

                n = sum(
                    len(r) if isinstance(r, LineBatch) else 1
                    for recs in records_per for r in recs
                )
                return n >= 50_000

            with fused_pump(force=dense_records()):
                for k, part in enumerate(participants):
                    try:
                        if records_per is not None:
                            records = records_per[k]
                        else:
                            records = self._solo_participant_records(
                                part, items, progress
                            )
                        self._commit_fused_participant(
                            part, records,
                            attempt0 if k == 0 else new_attempt_id(),
                            n_queries=len(participants),
                        )
                        committed += 1
                    except WorkerKilled:
                        raise  # fault injection: die like a real crash
                    except Exception:  # noqa: BLE001 — tenant re-runs solo
                        log.exception(
                            "fused participant %s task %d failed; its "
                            "scheduler will re-issue it",
                            part["job_id"], part["task_id"],
                        )
                    progress()  # stamp the still-pending participants
            spans_mod.complete(
                "map:task", t0_wall, time.time() - t0_wall, cat="map",
                assign_wait_s=round(self._assign_wait_s, 6),
                fused=len(participants),
            )
        self.metrics.inc("fused_map_attempts")
        self.metrics.observe("map_task_total", time.perf_counter() - t0)
        _H_MAP_TASK.observe(time.perf_counter() - t0)
        log.info(
            "fused map attempt served %d/%d co-tenant tasks (%s:%d + %d)",
            committed, len(participants), a.job_id, a.task_id,
            len(a.fused),
        )

    def _solo_participant_records(self, part: dict, items: list,
                                  progress) -> list:
        """The fused attempt's fallback: run ONE participant's ordinary
        map over the already-read items (its own configure + batch/plain
        map), exactly what a solo attempt of its task would compute."""
        self.app.configure(**part["app_options"])
        p_items = self._participant_items(items, part)
        has_progress = self.app.set_progress(progress)
        try:
            if self.app.map_batch_fn is not None:
                return self.app.map_batch_fn(p_items)
            out = []
            for name, data in p_items:
                if not isinstance(data, (bytes, bytearray, memoryview)):
                    with open(data, "rb") as f:
                        data = f.read()
                out.extend(self.app.map_fn(name, bytes(data)))
            return out
        finally:
            if has_progress:
                self.app.set_progress(None)

    @staticmethod
    def _participant_items(items: list, part: dict) -> list:
        """Re-label shared split items with THIS participant's member
        names (two tenants may address the same content through
        different paths — symlinks/hardlinks; record keys must carry
        each job's own names)."""
        p_names = list(part.get("filenames") or []) or [part.get("filename")]
        if len(p_names) != len(items):
            # fail safe, never key this tenant's records by the shared
            # split's (primary) names: the raise fails THIS participant's
            # fallback, its own scheduler re-issues the task solo
            raise RuntimeError(
                f"fused participant {part.get('job_id')!r} has "
                f"{len(p_names)} member names for a {len(items)}-item split"
            )
        return [(p_names[i], data) for i, (_nm, data) in enumerate(items)]

    def _commit_fused_participant(self, part: dict, records: list,
                                  attempt: str, n_queries: int) -> None:
        """One participant's commit leg: bind ITS job's data plane,
        bucketize with ITS n_reduce, write intermediates under ITS task
        id, publish ITS commit record, send ITS finished RPC — the exact
        solo-map commit protocol, replayed per tenant."""
        import contextlib

        jid, tid = part["job_id"], part["task_id"]
        self._rpc_job_id = jid
        self.job_id = jid
        bind = getattr(self.transport, "bind_job", None)
        if bind is not None:
            bind(jid)
        if self.spans is not None:
            # explicit job tag: split_by_job routes this record into the
            # PARTICIPANT's events.jsonl, not the primary's
            self.spans.add({
                "t": "instant", "name": "fuse:split", "cat": "fuse",
                "ts": time.time(), "job": jid, "worker": self.worker_id,
                "args": {"task": tid, "queries": n_queries},
            })
        # the commit leg's spans (map:shuffle, map:commit) carry THIS
        # participant's job/task tags — under the primary's ambient
        # context they would all route into the primary's events.jsonl
        # and its trace row would show K shuffle legs
        ctx = (
            spans_mod.task_context(
                self.spans, job=jid, worker=self.worker_id, task=tid,
                attempt=attempt, kind="map",
            )
            if self.spans is not None else contextlib.nullcontext()
        )
        with ctx:
            with spans_mod.span("map:shuffle", cat="map"):
                buckets = shuffle.bucketize(records, part["n_reduce"])
                self._fault("before_map_commit")
                produced, peer_meta = self._write_map_outputs(tid, buckets)
            payload: dict = {"parts": produced}
            if peer_meta is not None:
                payload["peer"] = peer_meta
            self._publish_commit("map", tid, attempt, payload)
            self._fault("before_map_finished")
            finished = rpc.TaskFinishedArgs(
                task_id=tid, job_id=jid, worker_id=self.worker_id,
                produced_parts=produced,
            )
            if peer_meta is not None:
                finished.peer_endpoint = peer_meta["endpoint"]
                finished.peer_parts = peer_meta["parts"]
            self.transport.map_finished(self._finished_args(finished))
        self.metrics.inc("map_tasks")

    # ---------------------------------------------------------------- reduce
    def _run_reduce(self, a: rpc.AssignTaskReply) -> None:
        from distributed_grep_tpu.runtime.store import new_attempt_id

        t0 = time.perf_counter()
        t0_wall = time.time()
        attempt = new_attempt_id()
        with self._task_ctx("reduce", a.task_id, attempt):
            try:
                self._reduce_attempt(a, attempt)
            except TaskAborted:
                # fenced off by a newer scheduler incarnation: this
                # attempt's shuffle cursor is meaningless there — walk
                # away (the re-issued attempt owns the commit) and poll
                # for fresh work
                log.warning("reduce task %d attempt abandoned: stale "
                            "scheduler epoch", a.task_id)
                self.metrics.inc("reduce_aborted")
                return
            spans_mod.complete(
                "reduce:task", t0_wall, time.time() - t0_wall, cat="reduce",
                assign_wait_s=round(self._assign_wait_s, 6),
            )
            self.transport.reduce_finished(self._finished_args(
                rpc.TaskFinishedArgs(
                    task_id=a.task_id, job_id=self._rpc_job_id,
                    worker_id=self.worker_id,
                )
            ))
        self.metrics.inc("reduce_tasks")
        self.metrics.observe("reduce_task_total", time.perf_counter() - t0)
        _H_REDUCE_TASK.observe(time.perf_counter() - t0)

    def _reduce_attempt(self, a: rpc.AssignTaskReply, attempt: str) -> None:
        import os

        self.app.configure(**a.app_options)
        if self.spill_dir:
            os.makedirs(self.spill_dir, exist_ok=True)
        # Two record sinks behind one loop:
        # * generic apps — bounded-memory sort-merge grouping: records
        #   spill to sorted on-disk runs past the cap and group-reduce as
        #   a streaming merge (runtime/extsort.py; the reference
        #   materializes the whole partition, worker.go:161-162).
        #   Associative apps expose reduce_stream_fn to keep hot keys
        #   O(1) too.  Output: one "key<TAB>value\n" line per key (the
        #   reference writes "key value", worker.go:111-124, but grep
        #   keys contain spaces — a tab keeps the k/v split unambiguous),
        #   keys in sorted order for determinism.
        # * identity-reduce apps (the grep apps — ``reduce_is_identity``
        #   on the module) — columnar batches collate in (file, line)
        #   order (runtime/columnar.IdentityCollator): records never
        #   expand to per-line Python objects, output files come out in
        #   the CLI's display order, and collation downstream is a plain
        #   merge (the reference sorts once, worker.go:161-169 — so do
        #   we).
        if getattr(self.app.module, "reduce_is_identity", False):
            from distributed_grep_tpu.runtime.columnar import IdentityCollator

            sink = IdentityCollator(
                memory_limit_bytes=self.reduce_memory_bytes,
                spill_dir=self.spill_dir,
            )
            chunks = sink.iter_output_blocks  # bytes per batch, str per KV
            progress_stride = 64  # chunks are whole batches: coarse
        else:
            sink = ExternalReducer(
                memory_limit_bytes=self.reduce_memory_bytes,
                spill_dir=self.spill_dir,
            )
            stream_fn = getattr(self.app, "reduce_stream_fn", None)

            def chunks():
                for k, v in sink.reduce(self.app.reduce_fn, stream_fn):
                    yield f"{k}\t{v}\n"

            progress_stride = 4096
        try:
            files_processed = 0
            lost = ""
            t_shuffle = time.time()
            while True:
                r = self.transport.reduce_next_file(
                    rpc.ReduceNextFileArgs(
                        task_id=a.task_id, files_processed=files_processed,
                        job_id=self._rpc_job_id, epoch=a.epoch,
                        worker_id=self.worker_id, lost_file=lost,
                    )
                )
                lost = ""
                if getattr(r, "abort", False):
                    raise TaskAborted(a.task_id)
                if r.done:
                    break
                if not r.next_file:
                    continue  # long-poll window expired; re-poll (worker.go:153-160)
                data = self._fetch_shuffle(r)
                if data is None:
                    # unfetchable peer output (producer gone / checksum
                    # mismatch / no relay copy): report it on the next
                    # poll WITHOUT advancing the cursor — the scheduler
                    # re-executes the producing map and this cursor waits
                    # for the fresh attempt
                    lost = r.next_file
                    continue
                sink.add_many(shuffle.decode_records(data))
                files_processed += 1
                self._fault("after_reduce_file")
            # the streaming shuffle leg: long-poll waits included (reduce
            # runs concurrently with maps, so much of this is pipeline wait)
            spans_mod.complete(
                "reduce:shuffle", t_shuffle, time.time() - t_shuffle,
                cat="reduce", files=files_processed,
            )
            with spans_mod.span("reduce:compute", cat="reduce"):
                self._write_reduce_output(a, chunks(), progress_stride)
        finally:
            if sink.spill_count:
                self.metrics.inc("reduce_spills", sink.spill_count)
            sink.close()
        self._publish_commit(
            "reduce", a.task_id, attempt, {"output": f"mr-out-{a.task_id}"}
        )

    def _fetch_shuffle(self, r: rpc.ReduceNextFileReply) -> bytes | None:
        """Fetch one shuffle file.  No peer metadata on the reply: the
        pre-peer relay read, byte-identical behavior (errors propagate —
        the daemon answered wrong, not a vanished peer).  Peer-held:
        fetch directly from the producer through the transport retry
        helpers, verify size + crc32, fall back to the daemon relay on
        the DECLARED failures (peer gone after bounded retries, HTTP
        error, checksum mismatch — a mixed/migrating cluster may hold a
        relay copy), and return None when both fail — the caller reports
        the file lost and the producing map re-executes."""
        name = r.next_file
        endpoint = getattr(r, "peer_endpoint", "")
        if not endpoint:
            data = self.transport.read_intermediate(name)
            if self.peer is not None:
                # relay route in a peer-shuffle deployment (a local/relay
                # co-worker produced this one) — route telemetry only;
                # peer-free runs emit nothing
                spans_mod.instant("shuffle:relay", cat="reduce", file=name)
            return data
        try:
            if self.peer is not None and endpoint == self.peer.endpoint:
                # reducer IS the producer: serve from our own spool
                data = self.peer.get_local(self._rpc_job_id, name)
            else:
                fetch = getattr(self.transport, "fetch_peer", None)
                if fetch is not None:
                    data = fetch(endpoint, self._rpc_job_id, name)
                else:
                    from distributed_grep_tpu.runtime.http_transport import (
                        fetch_peer_data,
                    )

                    data = fetch_peer_data(endpoint, self._rpc_job_id, name)
            from distributed_grep_tpu.runtime.peer import checksum

            if (r.peer_size and len(data) != r.peer_size) or (
                r.peer_checksum and checksum(data) != r.peer_checksum
            ):
                raise OSError(
                    f"peer shuffle integrity failure for {name}: got "
                    f"{len(data)} bytes, crc {checksum(data)} (expected "
                    f"{r.peer_size}, {r.peer_checksum})"
                )
            self.metrics.inc("peer_fetches")
            spans_mod.instant("shuffle:peer", cat="reduce", file=name,
                              bytes=len(data))
            return data
        except (OSError, RuntimeError) as e:
            # CoordinatorGone (retry schedule dry) is an OSError; an HTTP
            # error status surfaces as RuntimeError — the declared
            # fallback set.  Anything else (a bug) propagates.
            self.metrics.inc("peer_fetch_failures")
            log.warning("peer fetch of %s from %s failed (%s); trying the "
                        "daemon relay", name, endpoint, e)
        try:
            data = self.transport.read_intermediate(name)
        except (OSError, RuntimeError):
            # no relay copy either (the common pure-P2P case: the bytes
            # died with the producer) — lost output
            return None
        self.metrics.inc("relay_fallbacks")
        spans_mod.instant("shuffle:relay", cat="reduce", file=name,
                          fallback=True)
        return data

    def _write_reduce_output(self, a: rpc.AssignTaskReply, chunks,
                             progress_stride: int) -> None:
        """Spool the output chunks locally, then commit atomically (the
        temp-file + rename commit, worker.go:103) — output size never
        bounds on worker memory.  Throttled progress stamps keep a long
        merge alive past the sweep window (it has no RPC activity)."""
        import os
        import tempfile

        fd, spool = tempfile.mkstemp(prefix="dgrep-redout-",
                                     dir=self.spill_dir or None)
        try:
            progress = self._progress_fn("reduce", a.task_id, a.task_timeout_s)
            # Binary spool: columnar sinks yield pre-encoded bytes blocks
            # (native formatter); str chunks encode utf-8/surrogateescape —
            # exactly what the old text-mode writer did per write.
            with self.metrics.timer("reduce_compute"), \
                    trace.annotate(f"reduce_compute:{a.task_id}"), \
                    os.fdopen(fd, "wb") as out:
                for i, chunk in enumerate(chunks):
                    out.write(
                        chunk if isinstance(chunk, bytes)
                        else chunk.encode("utf-8", "surrogateescape")
                    )
                    if i % progress_stride == 0:
                        progress()
            self._fault("before_reduce_commit")
            wof = getattr(self.transport, "write_output_from_file", None)
            if wof is not None:
                wof(f"mr-out-{a.task_id}", spool)
            else:  # custom transports without the streaming commit
                with open(spool, "rb") as f:
                    self.transport.write_output(f"mr-out-{a.task_id}", f.read())
        finally:
            # the transport may have CONSUMED the spool (rename commit on
            # local data planes, runtime/store.put_from_file consume=True)
            if os.path.exists(spool):
                os.unlink(spool)

"""Cross-tenant scan-fusion planning — the service half of ops/fuse.py.

The daemon's assign loop calls into this module to decide which
co-running print-mode grep jobs may share ONE worker scan per map split:

* ``fusion_key(config)`` — a grouping key over everything EXCEPT the
  query (pattern/patterns/ignore_case): two jobs fuse only when their
  application, every other app option, and their split-planning window
  agree, so the fused attempt can run one engine configuration and each
  participant's post-processing is its own job's exact semantics.
* ``query_spec(options)`` — the (pattern, patterns, ignore_case) tuple
  ops/fuse.QuerySpec accepts, or None when this query must scan solo
  (empty patterns, backreference-bearing regexes, approx queries).
* ``split_identity(split)`` — CONTENT identity of a map split: per-member
  (realpath, size, mtime_ns, inode) from a fresh stat — the CorpusCache
  validator tuple, so "same content" here is exactly what makes the
  device corpus cache serve both tenants the same resident shards.

This module is deliberately free of ops/jax imports: eligibility runs on
the daemon's control plane at submit/assign time (a remote-worker daemon
must stay importable without the ops stack), and all stat work runs
OUTSIDE the service/scheduler locks (analyze: locked-blocking).

Knobs (registered in analysis/knobs.py, owned here):

* ``DGREP_SERVICE_FUSE`` — 0/false disables fusion planning entirely; the
  daemon's wire payloads, journals, and outputs are then byte-identical
  to a pre-fusion daemon (the ``fused`` reply field is elided when
  empty).
* ``DGREP_FUSE_MAX_QUERIES`` — cap on queries per fused attempt
  (default 8): bounds the union automaton's size and the blast radius of
  one lost worker (a timeout re-enqueues K tasks, each of which then
  re-runs solo or in a fresh fusion).
"""

from __future__ import annotations

import os

DEFAULT_FUSE_MAX_QUERIES = 8

# The one application the fused map attempt knows how to run (it must
# expose map_fused_fn); jobs on any other app never fuse.
FUSABLE_APPLICATION = "distributed_grep_tpu.apps.grep_tpu"

# A fused attempt whole-reads its split (GrepEngine.scan_batch): splits
# past this total size keep the streaming solo path instead of trading
# bounded memory for a shared dispatch.
MAX_FUSED_SPLIT_BYTES = 256 << 20

# The query keys a fused group may differ on; every OTHER app option must
# be equal across the group (fusion_key folds them in).
_QUERY_KEYS = ("pattern", "patterns", "ignore_case")


def env_service_fuse(default: bool = True) -> bool:
    """Cross-tenant fusion switch — the ONE parser of DGREP_SERVICE_FUSE.
    On by default; "0"/"false"/"no" turns planning off entirely (a true
    no-op: assignments, wire payloads, and outputs revert to the
    pre-fusion daemon byte for byte)."""
    raw = os.environ.get("DGREP_SERVICE_FUSE")
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in ("0", "false", "no")


def env_fuse_max_queries(default: int = DEFAULT_FUSE_MAX_QUERIES) -> int:
    """Queries-per-fused-attempt cap — the ONE parser of
    DGREP_FUSE_MAX_QUERIES (malformed keeps the default, matching
    env_batch_bytes' shrug-off policy; values below 2 clamp to 2, the
    smallest fusion — turning fusion OFF is DGREP_SERVICE_FUSE=0's job,
    not this knob's)."""
    raw = os.environ.get("DGREP_FUSE_MAX_QUERIES")
    if raw is None or raw == "":
        return default
    try:
        return max(2, int(raw))
    except ValueError:
        return default


def has_backref(rx: str) -> bool:
    """True when the regex uses any group-number-sensitive construct
    (numeric/named backreference, conditional group test) — joining such
    a pattern into an alternation silently repoints its groups.  Walks
    re's parse tree (the __main__._has_backref logic, re-homed here so
    the service can ask without importing the CLI); parse failures count
    as True (not fusable — the union builder could not host it anyway)."""
    try:
        import re._parser as parser  # 3.11+
    except ImportError:
        import sre_parse as parser  # 3.10

    def walk(node) -> bool:
        if isinstance(node, parser.SubPattern):
            return any(walk(item) for item in node)
        if isinstance(node, tuple):
            op = node[0]
            if op in (parser.GROUPREF, parser.GROUPREF_EXISTS):
                return True
            return any(walk(x) for x in node[1:])
        if isinstance(node, list):
            return any(walk(x) for x in node)
        return False

    try:
        return walk(parser.parse(rx))
    except Exception:  # noqa: BLE001 — unparseable: treat as unfusable
        return True


def query_spec(options: dict) -> tuple | None:
    """(pattern, patterns, ignore_case) when this job's query can join a
    fused union (ops/fuse.QuerySpec.normalize accepts the tuple), else
    None — the solo paths then serve it unchanged."""
    if options.get("max_errors"):
        return None  # approx queries have no union form
    pats = options.get("patterns")
    ic = bool(options.get("ignore_case"))
    if pats:
        norm = tuple(
            p.decode("utf-8", "surrogateescape") if isinstance(p, bytes)
            else str(p)
            for p in pats
        )
        if any(p == "" for p in norm):
            return None
        return (None, norm, ic)
    pat = options.get("pattern")
    if isinstance(pat, bytes):
        pat = pat.decode("utf-8", "surrogateescape")
    if not pat:
        return None  # empty pattern matches everything — solo is free
    if has_backref(pat):
        return None
    return (pat, None, ic)


def fusion_key(config) -> tuple | None:
    """Grouping key for a JobConfig's fused-eligibility, or None when the
    job can never fuse.  Jobs fuse only within one key: same application
    (grep_tpu — the app that implements map_fused_fn), same app options
    apart from the query itself, same split-planning window (so the two
    jobs' map splits over identical inputs align), and a query the union
    builder can host.  Print-mode only: count/presence queries ride
    stop-early streaming paths the fused batch scan does not reproduce."""
    if getattr(config, "application", None) != FUSABLE_APPLICATION:
        return None
    opts = config.effective_app_options()
    if opts.get("count_only") or opts.get("presence_only"):
        return None
    if opts.get("mesh_shape"):
        return None  # mesh engines bypass every cross-job cache — and fusion
    if query_spec(opts) is None:
        return None
    rest = {k: v for k, v in opts.items() if k not in _QUERY_KEYS}
    try:
        frozen = tuple(sorted((k, _freeze(v)) for k, v in rest.items()))
    except TypeError:
        return None  # unhashable exotic option: stay solo
    return (config.application, frozen, int(config.effective_batch_bytes()))


def follow_fusion_key(config) -> tuple | None:
    """Grouping key for FUSED STANDING QUERIES (round 21), or None when
    this follow job must run its own solo wake loop.  Two standing
    queries share one group wake — one suffix read + one union scan per
    (file, wake) — only when the batch ``fusion_key`` agrees (same app,
    same non-query options, a union-hostable query) AND they watch the
    SAME input set by realpath: follow cursors track file CONTENT as it
    grows, so the watched-identity half of the key is the resolved path
    set, not the CorpusCache validator tuple (size/mtime drift every
    append — that is the point of the tier).  Realpath is stat-ish work:
    call outside the service lock only (the _flush_follow_start
    context)."""
    if not getattr(config, "follow", False):
        return None
    base = fusion_key(config)
    if base is None:
        return None
    try:
        watched = tuple(sorted(
            os.path.realpath(os.fspath(f)) for f in config.input_files
        ))
    except (OSError, TypeError):
        return None
    if not watched:
        return None
    return (base, watched)


def _freeze(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    return v


def split_identity(split) -> tuple | None:
    """Content identity of one map split (a path, or a list of member
    paths): per-member (realpath, size, mtime_ns, inode) — the
    CorpusCache validator tuple.  None when any member cannot be statted
    or the split is too large to whole-read in a fused attempt.  Stat
    work: call OUTSIDE the service/scheduler locks only."""
    members = split if isinstance(split, (list, tuple)) else [split]
    out = []
    total = 0
    for m in members:
        try:
            real = os.path.realpath(os.fspath(m))
            st = os.stat(real)
        except OSError:
            return None
        total += int(st.st_size)
        out.append((real, int(st.st_size), int(st.st_mtime_ns),
                    int(st.st_ino)))
    if total > MAX_FUSED_SPLIT_BYTES:
        return None
    return tuple(out)


def plan_identities(map_splits: list) -> tuple[list, dict]:
    """(identities, index) for a job's planned map splits: identities[i]
    is split_identity(map_splits[i]) (None = unfusable split) and index
    maps identity -> task id (task ids are split indices by
    construction — runtime/scheduler seeds MapTask(i, files[i])).
    Runs at submit/resume time, outside every lock."""
    identities = [split_identity(s) for s in map_splits]
    index = {}
    for tid, ident in enumerate(identities):
        if ident is not None and ident not in index:
            index[ident] = tid
    return identities, index


def split_n_bytes(identity) -> int:
    """Total content bytes of a split identity (the planner's
    fusion_bytes_saved accounting — sizes were captured in the stat)."""
    return sum(v[1] for v in identity) if identity else 0

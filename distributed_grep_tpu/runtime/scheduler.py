"""Coordinator task scheduler — the reference's semantics, without busy-polls.

Reproduces map_reduce/coordinator.go's behavior:

* one map task per input file, seeded up front (coordinator.go:329-333);
  reduce partitions 0..n_reduce-1 seeded alongside (coordinator.go:334-337);
* long-polling AssignTask: blocks until a map split is available; after the
  map phase completes, hands out reduce partitions (coordinator.go:43-95);
* file->task dedup so a re-enqueued file keeps its task id
  (coordinator.go:53-58);
* monotonically increasing worker ids allocated at assignment
  (coordinator.go:68,:86);
* streaming shuffle: ReduceNextFile blocks until the next intermediate file
  for that partition commits, or returns done once the map phase is over and
  the cursor is exhausted — so reducers run concurrently with maps
  (coordinator.go:159-174);
* heartbeats stamped at assignment and on every next-file fetch
  (coordinator.go:62,:82,:162); a background sweeper re-enqueues any
  in-progress task idle >= task_timeout_s (coordinator.go:97-124);
* idempotent completion: duplicate MapFinished/ReduceFinished short-circuit
  (coordinator.go:131-134);
* Done() when both phases complete (coordinator.go:276-299) — without the
  reference's side effect of tearing down connections inside the predicate.

Where the reference busy-polls (10 ms in AssignTask :89,:92, 50 ms in
ReduceNextFile :172, 1 s sweeper :122), this scheduler blocks on a single
condition variable and notifies on every state change.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Optional

from distributed_grep_tpu.runtime import rpc
from distributed_grep_tpu.runtime.journal import TaskJournal
from distributed_grep_tpu.runtime.types import MapTask, ReduceTask, TaskState
from distributed_grep_tpu.utils import lockdep
from distributed_grep_tpu.utils import metrics as metrics_mod
from distributed_grep_tpu.utils.logging import get_logger
from distributed_grep_tpu.utils.metrics import Metrics
from distributed_grep_tpu.utils.spans import ClockSync, EventLog

log = get_logger("scheduler")

# Process-global typed instruments (utils/metrics.py round 15): scheduling
# latency + failure-detector activity, served at GET /metrics on both the
# one-shot coordinator and the service daemon.  Leaf locks — safe to
# touch under the scheduler lock.
_H_ASSIGN_POLL = metrics_mod.histogram("dgrep_assign_poll_seconds")
_H_MAP_PHASE = metrics_mod.histogram("dgrep_map_phase_seconds")
_H_REDUCE_PHASE = metrics_mod.histogram("dgrep_reduce_phase_seconds")
_C_REQUEUED = metrics_mod.counter("dgrep_tasks_requeued_total")
_C_QUARANTINED = metrics_mod.counter("dgrep_workers_quarantined_total")

# Consecutive attributed failures (task timeouts while holding the task)
# before a worker is quarantined.  One timeout is routine (a long GC pause,
# one slow disk); three in a row with no intervening success is a worker
# that keeps accepting work and keeps going dark — exactly the flaky-host
# pattern that otherwise captures a share of every job's tasks forever.
QUARANTINE_AFTER_FAILURES = 3
DEFAULT_QUARANTINE_S = 30.0
# Exponential backoff cap: repeated quarantine episodes double the window
# up to this many base windows (a worker flapping all day re-probations
# every ~8 windows instead of hourly-compounding to never).
_QUARANTINE_MAX_FACTOR = 8


def env_worker_quarantine_s(default: float = DEFAULT_QUARANTINE_S) -> float:
    """Base quarantine window for flaky workers — the ONE parser of
    DGREP_WORKER_QUARANTINE_S (malformed or <= 0 keeps the default,
    matching env_batch_bytes' shrug-off policy).  0 is deliberately not
    an off switch: quarantine is gated on attributed failures, and a
    deployment that wants it off sets the threshold unreachable by
    keeping workers healthy, not by a zero window that would re-admit a
    dark worker instantly."""
    raw = os.environ.get("DGREP_WORKER_QUARANTINE_S")
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


class WorkerHealth:
    """Per-worker consecutive-failure tracker with exponential-backoff
    quarantine — shared by every scheduler of a service daemon (one flaky
    worker must not be re-probationed per job) and owned privately by
    one-shot coordinators.  Thread-safe; all methods are O(1).

    A *failure* is an attributed task timeout: the sweeper re-enqueued a
    task while this worker held it.  A *success* is any committed task.
    After QUARANTINE_AFTER_FAILURES consecutive failures the worker is
    quarantined for base * 2^(episode-1) seconds (capped); while
    quarantined it receives no assignments — its polls park in the
    long-poll wait and return a retry with a retry_after_s hint.  Expiry
    is re-probation, not absolution: the failure streak resets to one
    step below the threshold, so one more timeout re-quarantines (with a
    doubled window) while one success clears the record."""

    # Bounded state over an unbounded worker-id stream (a service daemon
    # allocates a FRESH id per reconnect, and crashed workers leave their
    # records behind): past this many tracked workers the least-recently
    # touched non-quarantined records are dropped.
    MAX_TRACKED = 4096

    def __init__(self, base_s: float | None = None):
        self.base_s = (
            env_worker_quarantine_s() if base_s is None else float(base_s)
        )
        self._lock = lockdep.make_lock("worker-health")
        self._fails: dict[int, int] = {}  # consecutive attributed failures
        self._episodes: dict[int, int] = {}  # quarantine episodes so far
        self._until: dict[int, float] = {}  # monotonic expiry per worker
        self._touched: dict[int, float] = {}  # recency (prune order)
        self._polls: dict[int, float] = {}  # last assign poll per worker
        self.quarantined_total = 0  # counter: episodes ever entered
        # Optional fleet-timeline hook (round 19): the service points it
        # at DaemonLog staging so quarantine enter/expire/clear land on
        # daemon.jsonl exactly once per episode even with K per-job
        # schedulers sharing this tracker.  Called OUTSIDE self._lock
        # (the callback takes its own leaf lock); never raises upward.
        self.on_event = None

    def _emit(self, kind: str, **payload) -> None:
        cb = self.on_event
        if cb is not None:
            try:
                cb(kind, **payload)
            except Exception:  # noqa: BLE001 — telemetry, never fatal
                log.exception("worker-health event hook failed")

    def _prune_locked(self, now: float) -> None:
        if len(self._touched) <= self.MAX_TRACKED:
            return
        evictable = sorted(
            (wid for wid in self._touched
             if self._until.get(wid, 0.0) <= now),
            key=lambda wid: self._touched[wid],
        )
        for wid in evictable[: len(self._touched) - self.MAX_TRACKED]:
            for d in (self._fails, self._episodes, self._until,
                      self._touched, self._polls):
                d.pop(wid, None)

    def saw(self, worker_id: int) -> None:
        """Record an assign poll.  A worker loop is single-threaded: a
        poll AFTER an assignment proves it is NOT running that task — the
        evidence `record_failure` callers use to distinguish a lost
        assignment reply from a worker gone dark."""
        if worker_id < 0:
            return
        with self._lock:
            now = time.monotonic()
            self._polls[worker_id] = now
            self._touched[worker_id] = now
            self._prune_locked(now)

    def polled_since(self, worker_id: int, t: float) -> bool:
        """True when the worker has asked for work after monotonic ``t``."""
        with self._lock:
            return self._polls.get(worker_id, float("-inf")) > t

    def record_success(self, worker_id: int) -> None:
        if worker_id < 0:
            return
        with self._lock:
            had_episode = worker_id in self._episodes
            # drop the WHOLE record, _polls included: _prune_locked only
            # walks _touched, so an entry left in any sibling dict here
            # would leak for the daemon's lifetime
            self._fails.pop(worker_id, None)
            self._episodes.pop(worker_id, None)
            self._until.pop(worker_id, None)
            self._touched.pop(worker_id, None)
            self._polls.pop(worker_id, None)
        if had_episode:
            self._emit("quarantine_clear", worker=worker_id)

    def record_failure(self, worker_id: int) -> float:
        """Register an attributed failure; returns the quarantine window
        just entered (seconds), or 0.0 when the worker stays on
        probation."""
        if worker_id < 0:
            return 0.0
        episode = 0
        with self._lock:
            now = time.monotonic()
            self._touched[worker_id] = now
            self._prune_locked(now)
            if self._until.get(worker_id, 0.0) > now:
                return 0.0  # already quarantined: don't re-extend per sweep
            n = self._fails.get(worker_id, 0) + 1
            self._fails[worker_id] = n
            if n < QUARANTINE_AFTER_FAILURES:
                return 0.0
            ep = self._episodes.get(worker_id, 0) + 1
            self._episodes[worker_id] = ep
            episode = ep
            window = self.base_s * min(2 ** (ep - 1), _QUARANTINE_MAX_FACTOR)
            self._until[worker_id] = now + window
            # re-probation: one step below the threshold, so the next
            # failure after expiry re-quarantines immediately
            self._fails[worker_id] = QUARANTINE_AFTER_FAILURES - 1
            self.quarantined_total += 1
        self._emit("quarantine", worker=worker_id, episode=episode,
                   window_s=round(window, 3))
        return window

    def quarantine_remaining(self, worker_id: int) -> float:
        """Seconds of quarantine left for this worker (0.0 = assignable)."""
        with self._lock:
            until = self._until.get(worker_id)
            if until is None:
                return 0.0
            rem = until - time.monotonic()
            if rem > 0:
                return rem
            del self._until[worker_id]  # expired: re-probation
        self._emit("quarantine_expire", worker=worker_id)
        return 0.0

    def snapshot(self) -> dict:
        """Status view: active quarantines + the episode counter."""
        now = time.monotonic()
        with self._lock:
            active = {
                str(wid): round(until - now, 3)
                for wid, until in self._until.items() if until > now
            }
            return {
                "quarantined_total": self.quarantined_total,
                "active": active,
            }


def _split_label(members: tuple[str, ...]) -> str:
    """Display/journal label for a batched multi-file map split —
    deterministic for a given member list, so journal replay of the same
    job plan recognizes its own entries."""
    return f"{members[0]} (+{len(members) - 1} batched)"


def _producer_task_of(name: str) -> int | None:
    """The producing map task id of an intermediate file name
    ("mr-<tid>-<r>", the worker's own naming contract), or None for
    anything else-shaped.  Gates shuffle serves on the producer's
    COMPLETED state and resolves lost-output reports (peer shuffle,
    round 16) to the map task that must re-run."""
    parts = name.split("-")
    if len(parts) == 3 and parts[0] == "mr" and parts[1].isdigit():
        return int(parts[1])
    return None


class Scheduler:
    """Transport-agnostic coordinator state machine (thread-safe).

    ``files`` entries are either a single input path (one map task per
    file, the reference shape — coordinator.go:329-333) or a list of
    paths: a batched multi-file split (runtime/job.plan_map_splits packs
    the many-small-files regime so one map task — and one packed device
    dispatch — covers many sub-threshold files)."""

    def __init__(
        self,
        files: list[str],
        n_reduce: int,
        task_timeout_s: float = 10.0,
        sweep_interval_s: float = 1.0,
        app_options: Optional[dict[str, Any]] = None,
        journal: Optional[TaskJournal] = None,
        resume_entries: Optional[list[dict]] = None,
        metrics: Optional[Metrics] = None,
        commit_resolver: Optional[Any] = None,
        event_log: Optional[EventLog] = None,
        on_change: Optional[Any] = None,
        worker_health: Optional[WorkerHealth] = None,
        journal_gate: Optional[Any] = None,
        daemon_events: Optional[Any] = None,
    ):
        self.n_reduce = n_reduce
        self.task_timeout_s = task_timeout_s
        self.sweep_interval_s = sweep_interval_s
        self.app_options = dict(app_options or {})
        self.journal = journal
        self.metrics = metrics or Metrics()
        # commit_resolver(kind, task_id) -> winning task commit record
        # payload or None (WorkDir.resolve_task_commit, runtime/store.py).
        # When a record exists it — not the finished-RPC args — is the unit
        # of truth for what a completed task produced: a re-executed
        # straggler whose late RPC races the sweeper's re-issue can then
        # never register parts its winning attempt did not commit.
        self.commit_resolver = commit_resolver
        # Span pipeline (utils/spans.py): when an event log is wired in,
        # worker-shipped span records persist to events.jsonl and the
        # scheduler's own decisions (assignments, timeout re-enqueues,
        # commit registrations) are logged as coordinator-row events.
        # None = pipeline off: no file, no extra work on any RPC.
        self.event_log = event_log
        # Assignability callback for a MULTIPLEXING layer above (the
        # service daemon, runtime/service.py): its assign loop long-polls
        # across many schedulers on its own condition variable, which this
        # scheduler's internal notify cannot reach — called (outside the
        # lock) whenever work may have BECOME assignable here: a map-phase
        # completion (unlocks the reduce queue) or a timeout re-enqueue.
        # None (single-job coordinators) costs nothing.
        self.on_change = on_change
        # Flaky-worker quarantine (WorkerHealth above): the sweeper
        # attributes each timeout to the worker that held the task;
        # enough consecutive failures park that worker's assign polls
        # until an exponential-backoff window expires.  A service daemon
        # passes ONE shared instance to every job's scheduler (a flaky
        # worker is flaky for every tenant); one-shot coordinators get
        # their own.
        self.worker_health = worker_health or WorkerHealth()
        self._pending_events: list[dict] = []  # staged under the lock,
        # written by _flush_events after release
        # Journal completions are staged the same way (checked:
        # locked-blocking): TaskJournal fsyncs per record, and an fsync
        # inside the scheduler lock would stall every RPC handler behind
        # the disk on each commit.  The flush lock serializes write
        # batches end to end (the service registry-flush pattern);
        # durability-before-reply holds because map_finished /
        # reduce_finished flush in their `finally`, before the RPC reply
        # leaves the process.
        self._pending_journal: list[tuple] = []
        self._journal_flush_lock = lockdep.make_lock("journal-flush",
                                                     io_ok=True)
        # Daemon-scope write fence (round 18 HA failover): an optional
        # callable consulted by every journal flush batch before it
        # writes.  A False answer means this daemon lost the work-root
        # lease — the batch is DROPPED (the promoted daemon owns the
        # journal now; a stale interleaved line would poison its replay).
        # None (single-daemon, one-shot coordinators) skips the check
        # entirely.
        self.journal_gate = journal_gate
        # Fleet-timeline hook (round 19, runtime/daemon_log.py): a
        # callable(kind, **payload) the service points at DaemonLog
        # staging, called for daemon-consequential decisions (lost-output
        # revocations) — leaf-lock list append, safe under self._lock.
        # None (one-shot coordinators) costs nothing.
        self.daemon_events = daemon_events
        # (kind, task_id) pairs already journaled (staged or replayed):
        # a map task RE-COMPLETED after a lost-output re-execution (peer
        # shuffle, round 16) must not append a second map_done line —
        # the chaos matrix pins journal uniqueness per (kind, task), and
        # replay treats the first line as done anyway (re-execution is
        # deterministic, so the recorded parts still hold).
        self._journaled: set[tuple[str, int]] = set()
        self._span_seqs: dict[int, set[int]] = {}  # worker -> persisted
        # batch seqs (retry dedup, _persist_spans)
        self._span_seq_lock = lockdep.make_lock("span-seq")
        self._clock = ClockSync()
        # Per-worker liveness + shipped-metrics table (workers join
        # implicitly, so rows appear at first assignment/heartbeat):
        # worker_id -> {"seen": monotonic, "task": "map:3"|None,
        #               "metrics": last piggybacked counters snapshot,
        #               "clock_offset_s": ..., "rtt_s": ...}
        self.workers: dict[int, dict] = {}

        self._lock = lockdep.make_lock("scheduler")
        self._cond = threading.Condition(self._lock)

        # Task tables (MapData/ReduceData, helper_types.go:150-161).
        self.map_tasks: list[MapTask] = []
        for i, f in enumerate(files):
            if isinstance(f, (list, tuple)):
                members = tuple(str(m) for m in f)
                self.map_tasks.append(
                    MapTask(i, _split_label(members), files=members)
                )
            else:
                self.map_tasks.append(MapTask(i, f))
        self.reduce_tasks: list[ReduceTask] = [ReduceTask(i) for i in range(n_reduce)]
        self.file_to_task: dict[str, int] = {
            t.file: t.task_id for t in self.map_tasks
        }

        # Work queues (the buffered channels, coordinator.go:329-337).
        self._map_queue: deque[int] = deque(range(len(files)))
        self._reduce_queue: deque[int] = deque(range(n_reduce))

        self._next_worker_id = 0  # safeInt.get_and_increment (helper_types.go:45-79)
        # Incarnation epoch (rpc.AssignTaskReply.epoch): task_files
        # arrival order — and with it every reducer's files_processed
        # cursor — is only meaningful within ONE scheduler instance; a
        # restarted coordinator/daemon rebuilds the lists in replay
        # order, so shuffle fetches carrying another incarnation's epoch
        # are aborted (reduce_next_file), never served a file their
        # cursor would misindex.
        import uuid as _uuid

        self.epoch = _uuid.uuid4().hex[:12]
        self._stopped = False
        # Incremental completion counters: COMPLETED is terminal (the
        # sweeper only re-enqueues IN_PROGRESS tasks), so counting at the
        # transitions replaces the per-event O(n) sweeps over the task
        # tables that made a 2,000-file `grep -r` job quadratic (round 5).
        self._maps_completed = 0
        self._reduces_completed = 0
        # Phase-wall instrumentation anchors: construction -> last map
        # commit = map phase; that instant -> last reduce commit = reduce
        # phase.  Phases completed purely by journal replay observe
        # nothing (a resumed job's wall would misprice the live phase).
        self._phase_t0 = time.monotonic()
        self._reduce_t0: float | None = None
        # The map phase can complete MORE than once: a lost-output
        # revocation (peer shuffle) walks a COMPLETED map back to
        # UNASSIGNED and the re-execution re-crosses the phase boundary.
        # Observe the wall (and anchor _reduce_t0) at the FIRST crossing
        # only — a re-crossing's "map phase wall" would include the
        # elapsed reduce time.
        self._phase_observed = False

        if resume_entries:
            self._replay(resume_entries)

        self._sweeper = threading.Thread(
            target=self._sweep_loop, name="failure-detector", daemon=True
        )
        self._sweeper.start()

    # ------------------------------------------------------------------ replay
    def _resolve_commit(self, kind: str, task_id: int):
        """The winning task commit record payload, or None (no resolver /
        no record).  Resolver failures count as 'no record' — the RPC-args
        path still works, so a broken commits dir degrades, not crashes."""
        if self.commit_resolver is None:
            return None
        try:
            return self.commit_resolver(kind, task_id)
        except Exception:  # noqa: BLE001 — degrade to RPC-args truth
            log.exception("commit record resolution failed for %s %d", kind, task_id)
            return None

    def _replay(self, entries: list[dict]) -> None:
        """Apply journal entries so a restarted coordinator skips done work."""
        for e in entries:
            if e.get("kind") == "map_done":
                tid = e["task_id"]
                if 0 <= tid < len(self.map_tasks):
                    t = self.map_tasks[tid]
                    files_e = e.get("files")
                    if t.file != e.get("file") or (
                        # batched split: the member list must match too (a
                        # re-planned batch with the same first file and
                        # count — e.g. member sizes changed between runs —
                        # is a DIFFERENT split and must re-run)
                        files_e is not None and tuple(files_e) != t.files
                    ):
                        # Input list changed/reordered since the journal was
                        # written: this entry describes a different split, so
                        # the task must run again.
                        log.warning(
                            "journal entry for map task %d names %r but task file "
                            "is %r; ignoring entry",
                            tid,
                            e.get("file"),
                            t.file,
                        )
                        continue
                    parts = e.get("parts", [])
                    peer = None
                    if e.get("has_record"):
                        # This completion was committed via a task commit
                        # record — re-resolve it as the unit of truth.  A
                        # journal entry whose record vanished is stale
                        # (someone swept the commits dir): re-run the task
                        # rather than trust unverifiable state.
                        record = self._resolve_commit("map", tid)
                        if record is None:
                            log.warning(
                                "journal says map task %d committed via record "
                                "but no valid record resolves; re-running", tid,
                            )
                            continue
                        # malformed record (no "parts"): keep the journal's
                        parts = record.get("parts", parts)
                        # peer-held output (round 16): the record's
                        # metadata survives a coordinator restart — if
                        # the producer also died, the first fetch fails
                        # and the lost-output path re-runs this task
                        if isinstance(record.get("peer"), dict):
                            peer = record["peer"]
                    if t.state is not TaskState.COMPLETED:
                        t.state = TaskState.COMPLETED
                        t.peer = peer
                        self._journaled.add(("map", tid))
                        self._register_map_outputs(tid, parts)
                        if tid in self._map_queue:
                            self._map_queue.remove(tid)
            elif e.get("kind") == "reduce_done":
                tid = e["task_id"]
                if 0 <= tid < len(self.reduce_tasks):
                    if e.get("has_record") and self._resolve_commit("reduce", tid) is None:
                        log.warning(
                            "journal says reduce task %d committed via record "
                            "but no valid record resolves; re-running", tid,
                        )
                        continue
                    t = self.reduce_tasks[tid]
                    t.state = TaskState.COMPLETED
                    self._journaled.add(("reduce", tid))
                    if tid in self._reduce_queue:
                        self._reduce_queue.remove(tid)
        # one-time O(n) resync of the incremental counters after replay
        self._maps_completed = sum(
            t.state is TaskState.COMPLETED for t in self.map_tasks
        )
        self._reduces_completed = sum(
            t.state is TaskState.COMPLETED for t in self.reduce_tasks
        )
        # A phase completed purely by replay observes nothing (the
        # round-15 contract) — and must not observe later either, when a
        # lost-output revocation makes a live commit re-cross it.
        if self.map_tasks and self._map_phase_done_locked():
            self._phase_observed = True
        log.info(
            "journal replay: %d map + %d reduce tasks already complete",
            self._maps_completed, self._reduces_completed,
        )

    # ----------------------------------------------------------- observability
    def _event(self, name: str, **args) -> None:
        """Coordinator-row event (no worker tag -> tid 0 in trace-export).
        No-op without an event log.  Call sites hold the scheduler lock, so
        the record is only STAGED here; `_flush_events` writes it to disk
        after the lock is released — a slow work-dir filesystem must not
        stall every RPC handler behind a flush inside the global lock."""
        if self.event_log is None:
            return
        self._pending_events.append({
            "t": "instant", "name": name, "cat": "sched",
            "ts": time.time(), **({"args": args} if args else {}),
        })

    def _flush_events(self) -> None:
        """Write staged coordinator events outside the scheduler lock.
        Never raises — telemetry must not take the control plane down."""
        if self.event_log is None:
            return
        with self._lock:
            if not self._pending_events:
                return
            pending, self._pending_events = self._pending_events, []
        self._persist_spans(pending)

    def _flush_journal(self) -> None:
        """Write staged journal completions outside the scheduler lock —
        TaskJournal fsyncs per record, exactly the filesystem work the
        scheduler lock must never hold (checked: locked-blocking).  The
        flush lock makes swap + append one ordered unit; a journal
        closed by a racing job teardown absorbs the write (the entry
        only re-runs an already-finished task after a restart).  Never
        raises — a full disk degrades crash-resume, not the control
        plane."""
        if self.journal is None:
            return
        with self._journal_flush_lock:
            self._write_staged_journal()

    def close_journal(self) -> None:
        """Flush staged completions, then close the journal — one ordered
        unit under the flush lock, so a job teardown can never close the
        file between a completion's staging and its write (a completion
        stages BEFORE it notifies done, so anything a finalizer could
        have observed is durable before the close)."""
        if self.journal is None:
            return
        with self._journal_flush_lock:
            self._write_staged_journal()
            self.journal.close()

    def _write_staged_journal(self) -> None:
        """The write half of _flush_journal; caller holds the flush lock."""
        with self._lock:
            if not self._pending_journal:
                return
            pending, self._pending_journal = self._pending_journal, []
        if self.journal_gate is not None and not self.journal_gate():
            # deposed (lease lost): drop the batch — commit records keep
            # the tasks' truth; the promoted daemon's replay re-resolves
            # them without ever seeing a stale interleaved line
            log.warning("journal flush fenced: lease lost, %d staged "
                        "entries dropped", len(pending))
            return
        for kind, task_id, file, parts, has_record, files in pending:
            try:
                if kind == "map":
                    self.journal.map_completed(
                        task_id, file, parts, has_record=has_record,
                        files=files,
                    )
                else:
                    self.journal.reduce_completed(
                        task_id, has_record=has_record
                    )
            except ValueError:
                # closed by job teardown racing a late completion: the
                # task is committed either way (commit records), the
                # journal line only skipped a restart's re-run
                log.warning(
                    "journal append after close dropped (%s task %d)",
                    kind, task_id,
                )
            except OSError:
                log.exception(
                    "journal append failed for %s task %d", kind, task_id
                )

    def _persist_spans(self, recs: list[dict], worker_id: int = -1,
                       seq: int = -1) -> None:
        """Persist a span batch.  (worker_id, seq) is the worker's batch
        counter: a transport-level RPC retry (grace-heartbeat re-POSTs,
        the finished RPC's 15 s retry loop) reships the SAME batch after
        the coordinator may already have processed it — dedup here keeps
        events.jsonl covering each attempt exactly once."""
        if self.event_log is None or not recs:
            return
        if seq >= 0 and worker_id >= 0:
            with self._span_seq_lock:
                seen = self._span_seqs.setdefault(worker_id, set())
                if seq in seen:
                    return
                seen.add(seq)
        try:
            self.event_log.write_many(recs)
        except Exception:  # noqa: BLE001
            log.exception("event log write failed")

    def _worker_seen(self, worker_id: int, task: str | None = ...,
                     metrics: dict | None = None) -> None:
        """Stamp a worker row (call under the lock).  `task` semantics:
        unspecified (Ellipsis) keeps the current in-flight marker."""
        if worker_id < 0:
            return
        info = self.workers.setdefault(worker_id, {"task": None})
        info["seen"] = time.monotonic()
        if task is not ...:
            info["task"] = task
        if metrics is not None:
            # the per-process source token rides only for the service's
            # delta tracker — it is not a counter, keep it out of the
            # /status worker rows (the dict is built fresh per RPC)
            metrics.pop("proc", None)
            info["metrics"] = metrics

    def _observe_clock(self, args: rpc.HeartbeatArgs,
                       recv_at: float) -> None:
        """Fold a heartbeat's clock observation in (recv_at = the wall
        clock at RPC arrival, captured by the caller before any span
        persistence or lock wait); persist a worker_clock record when the
        estimate moves >5 ms (trace-export reads the LAST record per
        worker)."""
        prev = self._clock.offsets.get(args.worker_id)
        off = self._clock.observe(
            args.worker_id, args.sent_at, recv_at, args.rtt_s
        )
        if off is None:
            return
        info = self.workers.get(args.worker_id)
        if info is not None:
            info["clock_offset_s"] = off
            info["rtt_s"] = self._clock.rtts.get(args.worker_id)
        if self.event_log is not None and (
            prev is None or abs(off - prev) > 0.005
        ):
            # staged like _event: callers hold the scheduler lock
            self._pending_events.append({
                "t": "worker_clock", "worker": args.worker_id,
                "offset_s": round(off, 6),
                "rtt_s": round(self._clock.rtts.get(args.worker_id, 0.0), 6),
                "ts": time.time(),
            })

    def worker_status(self) -> dict:
        """Per-worker liveness + shipped aggregates for GET /status: last
        heartbeat age, in-flight task, and the latest piggybacked Metrics
        counters (bytes_scanned / gbps / retries / spills)."""
        now = time.monotonic()
        with self._lock:
            out = {}
            for wid, info in sorted(self.workers.items()):
                row: dict = {
                    "last_heartbeat_age_s": round(now - info["seen"], 3),
                    "task": info.get("task"),
                }
                if info.get("metrics") is not None:
                    row["metrics"] = info["metrics"]
                if info.get("clock_offset_s") is not None:
                    row["clock_offset_s"] = round(info["clock_offset_s"], 6)
                q = self.worker_health.quarantine_remaining(wid)
                if q > 0:
                    row["quarantined_s"] = round(q, 3)
                out[str(wid)] = row
            return out

    def inflight_status(self) -> list[dict]:
        """Every IN_PROGRESS task with its heartbeat age and any active
        grace window — stragglers visible before the sweeper fires."""
        now = time.monotonic()
        out = []
        with self._lock:
            for kind, table in (("map", self.map_tasks),
                                ("reduce", self.reduce_tasks)):
                for t in table:
                    if t.state is TaskState.IN_PROGRESS:
                        age = now - t.timestamp
                        row = {
                            "type": kind, "task_id": t.task_id,
                            "attempts": t.attempts,
                            "heartbeat_age_s": round(age, 3),
                        }
                        if t.grace_s:
                            row["grace_s"] = t.grace_s
                            row["grace_remaining_s"] = round(
                                max(0.0, t.grace_s - age), 3
                            )
                        out.append(row)
        return out

    # ----------------------------------------------------------------- assign
    def assign_task(self, args: rpc.AssignTaskArgs, timeout: float = 30.0) -> rpc.AssignTaskReply:
        """Long-poll for work.  Blocks until a task is available, the job is
        done (reply JOB_DONE), or `timeout` elapses (reply JOB_DONE only if
        actually done; otherwise an empty retry reply with task_id == -2)."""
        deadline = _Deadline(timeout)
        t0 = time.monotonic()
        try:
            return self._assign_task_locked(args, deadline)
        finally:
            if timeout > 0:
                # real long-polls only: the service daemon sweeps every
                # running job's scheduler with timeout=0 per pass, and
                # those sub-millisecond probes would swamp the latency
                # signal (the daemon observes its own outer poll).
                _H_ASSIGN_POLL.observe(time.monotonic() - t0)
            self._flush_events()

    def _assign_task_locked(self, args: rpc.AssignTaskArgs,
                            deadline: "_Deadline") -> rpc.AssignTaskReply:
        with self._cond:
            worker_id = args.worker_id
            if worker_id < 0:
                worker_id = self._next_worker_id
                self._next_worker_id += 1
            # BEFORE any assignment stamp: a poll-then-assign in one call
            # must read as polled-before-held (lost-reply attribution)
            self.worker_health.saw(worker_id)
            while True:
                if self._stopped or self._done_locked():
                    return rpc.AssignTaskReply(
                        assignment=rpc.Assignment.JOB_DONE, worker_id=worker_id
                    )
                # Quarantined workers get no work: park in the long-poll
                # (waiting, not spinning — a tight retry loop against the
                # control plane is itself a failure mode) and answer a
                # retry with a client backoff hint at the window edge.
                quarantine_s = self.worker_health.quarantine_remaining(
                    worker_id
                )
                if quarantine_s > 0:
                    remaining = deadline.remaining()
                    if remaining <= 0:
                        return rpc.AssignTaskReply(
                            assignment="retry", task_id=-2,
                            worker_id=worker_id,
                            retry_after_s=round(quarantine_s, 3),
                        )
                    self._cond.wait(timeout=min(remaining, quarantine_s,
                                                self.sweep_interval_s))
                    continue
                while self._map_queue and (
                    self.map_tasks[self._map_queue[0]].state is not TaskState.UNASSIGNED
                ):
                    # Stale entry: the task timed out, was re-enqueued, and the
                    # original worker then completed it — never re-issue a
                    # COMPLETED (or already re-assigned) task.
                    self._map_queue.popleft()
                if self._map_queue:
                    tid = self._map_queue.popleft()
                    task = self.map_tasks[tid]
                    # file_to_task dedup keeps the same task id on re-issue
                    # (coordinator.go:53-58); queue entries are task ids here
                    # so the invariant holds by construction.
                    task.state = TaskState.IN_PROGRESS
                    task.heartbeat()
                    task.attempts += 1
                    task.worker = worker_id
                    task.stamped = False  # no worker-side evidence yet
                    task.fused_claim = False  # normal assign: chargeable
                    self.metrics.inc("map_assigned")
                    self._worker_seen(worker_id, task=f"map:{tid}")
                    self._event("assign_map", task=tid, worker=worker_id,
                                attempt=task.attempts, file=task.file)
                    log.debug("assign map task %d (%s) -> worker %d", tid, task.file, worker_id)
                    return rpc.AssignTaskReply(
                        assignment=rpc.Assignment.MAP,
                        filename=task.file,
                        filenames=list(task.files),  # batched split members
                        task_id=tid,
                        n_reduce=self.n_reduce,
                        worker_id=worker_id,
                        app_options=self.app_options,
                        task_timeout_s=self.task_timeout_s,
                        epoch=self.epoch,
                    )
                while self._reduce_queue and (
                    self.reduce_tasks[self._reduce_queue[0]].state is not TaskState.UNASSIGNED
                ):
                    self._reduce_queue.popleft()  # stale entry (see map queue above)
                if self._map_phase_done_locked() and self._reduce_queue:
                    tid = self._reduce_queue.popleft()
                    task = self.reduce_tasks[tid]
                    task.state = TaskState.IN_PROGRESS
                    task.heartbeat()
                    task.attempts += 1
                    task.worker = worker_id
                    task.stamped = False  # see the map branch above
                    self.metrics.inc("reduce_assigned")
                    self._worker_seen(worker_id, task=f"reduce:{tid}")
                    self._event("assign_reduce", task=tid, worker=worker_id,
                                attempt=task.attempts)
                    log.debug("assign reduce task %d -> worker %d", tid, worker_id)
                    return rpc.AssignTaskReply(
                        assignment=rpc.Assignment.REDUCE,
                        task_id=tid,
                        n_reduce=self.n_reduce,
                        worker_id=worker_id,
                        app_options=self.app_options,
                        task_timeout_s=self.task_timeout_s,
                        epoch=self.epoch,
                    )
                remaining = deadline.remaining()
                if remaining <= 0:
                    return rpc.AssignTaskReply(
                        assignment=rpc.Assignment.JOB_DONE if self._done_locked() else "retry",
                        task_id=-2,
                        worker_id=worker_id,
                    )
                self._cond.wait(timeout=min(remaining, self.sweep_interval_s))

    def claim_map_task(self, task_id: int, worker_id: int) -> dict | None:
        """Claim one SPECIFIC idle map task for a fused attempt (the
        service's cross-tenant scan fusion, runtime/fusion.py): the
        co-tenant's task joins another job's assignment, so this is the
        assign_task map branch minus the queue pop — the stale queue
        entry is skipped by the assign loop's UNASSIGNED check, exactly
        like a timeout re-enqueue's leftovers.  First attempts only: a
        task that already timed out once re-runs solo (fusion is a fast
        path; a fused-attempt-specific failure must not loop).  Returns
        the assignment fields for the fused reply entry, or None (not
        idle / retried / stopped — the planner then simply skips this
        tenant).  State moves only under the lock; events flush after
        release (checked: locked-blocking)."""
        try:
            with self._cond:
                if self._stopped or not 0 <= task_id < len(self.map_tasks):
                    return None
                task = self.map_tasks[task_id]
                if task.state is not TaskState.UNASSIGNED or task.attempts:
                    return None
                task.state = TaskState.IN_PROGRESS
                task.heartbeat()
                task.attempts += 1
                task.worker = worker_id
                task.stamped = False  # no worker-side evidence yet
                # Quarantine attribution: a fused EXTRA's timeout is never
                # charged (see the sweeper) — K participant schedulers
                # each sharing one WorkerHealth would otherwise count one
                # lost fused attempt as K consecutive failures and
                # insta-quarantine the worker; the PRIMARY assignment's
                # timeout carries the one charge for the shared event.
                task.fused_claim = True
                self.metrics.inc("map_assigned")
                self.metrics.inc("fused_assigned")
                self._worker_seen(worker_id, task=f"map:{task_id}")
                self._event("assign_map", task=task_id, worker=worker_id,
                            attempt=task.attempts, file=task.file,
                            fused=True)
                log.debug("fuse-claim map task %d (%s) -> worker %d",
                          task_id, task.file, worker_id)
                return {
                    "task_id": task_id,
                    "filename": task.file,
                    "filenames": list(task.files),
                    "n_reduce": self.n_reduce,
                    "app_options": self.app_options,
                    "task_timeout_s": self.task_timeout_s,
                    "epoch": self.epoch,
                }
        finally:
            self._flush_events()

    # ------------------------------------------------------------- completion
    def _notify_change(self) -> None:
        """Wake the multiplexing layer's assign loop (see on_change).
        Never raises — a broken callback must not fail a task commit."""
        cb = self.on_change
        if cb is None:
            return
        try:
            cb()
        except Exception:  # noqa: BLE001 — advisory wakeup only
            log.exception("scheduler on_change callback failed")

    def map_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply:
        """Idempotent map commit (coordinator.go:126-148)."""
        record = self._resolve_commit("map", args.task_id)
        self._persist_spans(args.spans, args.worker_id, args.spans_seq)
        try:
            return self._map_finished_locked(args, record)
        finally:
            self._flush_journal()  # fsync BEFORE the reply leaves
            self._flush_events()
            self._notify_change()  # map-phase completion unlocks reduces

    def _map_finished_locked(self, args: rpc.TaskFinishedArgs,
                             record) -> rpc.TaskFinishedReply:
        with self._cond:
            self._worker_seen(args.worker_id, task=None, metrics=args.metrics)
            # any completed task — duplicates included — is a live,
            # functional worker: clear its failure streak
            self.worker_health.record_success(args.worker_id)
            task = self.map_tasks[args.task_id]
            if task.state is TaskState.COMPLETED:
                return rpc.TaskFinishedReply(ok=True)  # duplicate absorbed (:131-134)
            task.state = TaskState.COMPLETED
            self._maps_completed += 1
            # The task commit record (published before this RPC) is the
            # unit of truth for the produced partitions; the RPC args are
            # the fallback for transports without commit records — and for
            # a malformed record missing "parts" (the data plane accepts
            # any small JSON body; malformed degrades, never crashes).
            parts = args.produced_parts
            if record is not None and "parts" in record:
                parts = record["parts"]
            # Peer-held output metadata (round 16): the LIVE attempt's
            # args win over the resolved record — record resolution picks
            # the lexicographically-smallest attempt, which after a
            # lost-output re-execution can still be the DEAD producer's;
            # registering the freshly-finished attempt's endpoint is what
            # lets recovery converge (a wrong endpoint only ever costs
            # one more lost-output round, never serves wrong bytes — the
            # checksum gate).  Relay commits carry neither and clear it.
            peer = None
            if record is not None and isinstance(record.get("peer"), dict):
                peer = record["peer"]
            if args.peer_endpoint:
                peer = {"endpoint": args.peer_endpoint,
                        "worker": args.worker_id,
                        "parts": dict(args.peer_parts or {})}
            task.peer = peer
            self._register_map_outputs(args.task_id, parts)
            self.metrics.inc("map_completed")
            if self._map_phase_done_locked() and not self._phase_observed:
                self._phase_observed = True
                now = time.monotonic()
                self._reduce_t0 = now
                _H_MAP_PHASE.observe(now - self._phase_t0)
            if self.journal and ("map", args.task_id) not in self._journaled:
                # staged under the lock, at most once per task — the
                # COMPLETED transition gates duplicates within one
                # completion, the _journaled set gates RE-completions
                # after a lost-output re-execution (peer shuffle);
                # fsync'd by _flush_journal after release
                self._journaled.add(("map", args.task_id))
                self._pending_journal.append((
                    "map", args.task_id, task.file, parts,
                    record is not None, list(task.files) or None,
                ))
            self._event("map_committed", task=args.task_id,
                        worker=args.worker_id, parts=len(parts),
                        has_record=record is not None)
            log.info(
                "map task %d done (%d/%d)",
                args.task_id, self._maps_completed, len(self.map_tasks),
            )
            self._cond.notify_all()
            return rpc.TaskFinishedReply(ok=True)

    def _register_map_outputs(self, map_task_id: int, produced_parts: list[int]) -> None:
        """Register committed intermediate files with their reduce partitions —
        only partitions the map actually produced (coordinator.go:139-141)."""
        for r in produced_parts:
            if 0 <= r < self.n_reduce:
                name = f"mr-{map_task_id}-{r}"
                if name not in self.reduce_tasks[r].task_files:
                    self.reduce_tasks[r].task_files.append(name)

    def reduce_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply:
        record = self._resolve_commit("reduce", args.task_id)
        self._persist_spans(args.spans, args.worker_id, args.spans_seq)
        try:
            return self._reduce_finished_locked(args, record)
        finally:
            self._flush_journal()  # fsync BEFORE the reply leaves
            self._flush_events()

    def _reduce_finished_locked(self, args: rpc.TaskFinishedArgs,
                                record) -> rpc.TaskFinishedReply:
        with self._cond:
            self._worker_seen(args.worker_id, task=None, metrics=args.metrics)
            self.worker_health.record_success(args.worker_id)
            task = self.reduce_tasks[args.task_id]
            if task.state is not TaskState.COMPLETED:
                task.state = TaskState.COMPLETED
                self._reduces_completed += 1
                self.metrics.inc("reduce_completed")
                if self._done_locked():
                    _H_REDUCE_PHASE.observe(
                        time.monotonic()
                        - (self._reduce_t0 or self._phase_t0)
                    )
                if self.journal and (
                    ("reduce", args.task_id) not in self._journaled
                ):
                    # staged like the map branch; see _flush_journal
                    self._journaled.add(("reduce", args.task_id))
                    self._pending_journal.append((
                        "reduce", args.task_id, None, None,
                        record is not None, None,
                    ))
                self._event("reduce_committed", task=args.task_id,
                            worker=args.worker_id,
                            has_record=record is not None)
                log.info(
                    "reduce task %d done (%d/%d)",
                    args.task_id, self._reduces_completed, self.n_reduce,
                )
            self._cond.notify_all()
            return rpc.TaskFinishedReply(ok=True)

    # ------------------------------------------------------ streaming shuffle
    def reduce_next_file(
        self, args: rpc.ReduceNextFileArgs, timeout: float = 30.0
    ) -> rpc.ReduceNextFileReply:
        """The pipelined shuffle feed (coordinator.go:159-174): block until the
        reducer's next intermediate file exists, or the map phase is done and
        the cursor is exhausted (done=True).  Doubles as a heartbeat (:162).

        Peer shuffle (round 16): a reply for a peer-held file carries the
        producing worker's endpoint + size + crc32 (wire-elided
        otherwise); an ``args.lost_file`` report re-enqueues the producing
        MAP task (``_report_lost_locked``) and this cursor then WAITS for
        the re-executed attempt — its file entry is gated on the
        producer's COMPLETED state, exactly like a file that has not
        arrived yet."""
        deadline = _Deadline(timeout)
        if args.epoch and args.epoch != self.epoch:
            # a reduce attempt from a PREVIOUS scheduler incarnation (it
            # outlived a daemon restart through its transport retries):
            # its files_processed cursor indexes the OLD task_files
            # arrival order — serving it from the rebuilt list would feed
            # it duplicate/missing shuffle files and its commit could WIN
            # attempt resolution with wrong bytes.  Abort the attempt;
            # the re-issued one owns this incarnation.  Checked BEFORE
            # any lost-output report is honored: a zombie must not
            # re-enqueue this incarnation's completed maps.
            log.warning(
                "aborting reduce attempt for task %d: stale scheduler "
                "epoch %s (current %s)", args.task_id, args.epoch,
                self.epoch,
            )
            return rpc.ReduceNextFileReply(abort=True)
        requeued = False
        try:
            with self._cond:
                if args.lost_file:
                    requeued = self._report_lost_locked(args)
                    if requeued:
                        # the reporter is ABORTED (and its task
                        # re-enqueued) along with the map re-enqueue: its
                        # cursor cannot advance until the map re-runs,
                        # and a worker parked in a gated long-poll is a
                        # worker that cannot run that map — with a small
                        # pool (every live worker holding a reduce) the
                        # job would deadlock.  Freed workers serve the
                        # map queue first, so progress is guaranteed
                        # with any one live worker; the re-issued reduce
                        # attempt re-fetches from the fresh metadata.
                        return rpc.ReduceNextFileReply(abort=True)
                task = self.reduce_tasks[args.task_id]
                while True:
                    task.heartbeat()
                    if args.worker_id < 0 or args.worker_id == task.worker:
                        # the CURRENT assignee demonstrably holds it; a
                        # same-life straggler's fetch must not plant the
                        # evidence that would charge the reassigned worker
                        task.stamped = True
                    if args.files_processed < len(task.task_files):
                        reply = self._serve_file_locked(
                            task.task_files[args.files_processed]
                        )
                        if reply is not None:
                            return reply
                        # producer re-executing (lost output): hold the
                        # cursor like a not-yet-arrived file — fall
                        # through to the wait
                    elif self._map_phase_done_locked():
                        return rpc.ReduceNextFileReply(done=True)
                    remaining = deadline.remaining()
                    if remaining <= 0:
                        # Not done — client should re-poll (long-poll
                        # window expired).
                        return rpc.ReduceNextFileReply(next_file="", done=False)
                    self._cond.wait(
                        timeout=min(remaining, self.sweep_interval_s)
                    )
        finally:
            self._flush_events()
            if requeued:
                self._notify_change()  # the map is assignable again

    def _serve_file_locked(self, name: str) -> rpc.ReduceNextFileReply | None:
        """One servable shuffle entry, or None while its producing map
        task is being re-executed (lost peer output — pre-peer this state
        was unreachable: files registered only at completion and were
        never revoked).  Peer-held entries carry the fetch metadata."""
        tid = _producer_task_of(name)
        mt = (self.map_tasks[tid]
              if tid is not None and 0 <= tid < len(self.map_tasks)
              else None)
        if mt is not None and mt.state is not TaskState.COMPLETED:
            return None
        reply = rpc.ReduceNextFileReply(next_file=name, done=False)
        if mt is not None and mt.peer:
            meta = mt.peer.get("parts", {}).get(name.rsplit("-", 1)[1])
            if meta:
                reply.peer_endpoint = str(mt.peer.get("endpoint", ""))
                reply.peer_size = int(meta[0])
                reply.peer_checksum = str(meta[1])
        return reply

    def _report_lost_locked(self, args: rpc.ReduceNextFileArgs) -> bool:
        """Handle a reducer's lost-output report (caller holds the lock):
        re-enqueue the producing map task — its output died with its
        worker, the load-bearing P2P fault path — and charge the vanished
        producer's health record.  Returns True when a task was actually
        re-enqueued (first report wins; later reporters of the same task
        see it already re-running and simply wait).  Only PEER-HELD
        completed outputs are revocable: a relay 404 is a data-plane bug
        the store layer owns, not a lost worker."""
        name = args.lost_file
        tid = _producer_task_of(name)
        if tid is None or not 0 <= tid < len(self.map_tasks):
            log.warning("ignoring lost-output report for %r: not an "
                        "intermediate file name", name)
            return False
        task = self.map_tasks[tid]
        if task.state is not TaskState.COMPLETED or not task.peer:
            return False
        producer = int(task.peer.get("worker", -1))
        log.warning(
            "map task %d output %s lost with its producer (worker %d, "
            "reported by worker %d); re-executing", tid, name, producer,
            args.worker_id,
        )
        task.state = TaskState.UNASSIGNED
        task.peer = None
        task.worker = -1
        task.stamped = False
        self._maps_completed -= 1
        self._map_queue.append(tid)
        self.metrics.inc("maps_lost_output")
        self.metrics.inc("map_retries")
        self.metrics.inc("tasks_requeued")
        _C_REQUEUED.inc()
        # SLO counter (round 19): created lazily at this event site so
        # deployments that never lose an output never render the series
        metrics_mod.counter("dgrep_maps_lost_output_total").inc()
        self._event("map_lost_output", task=tid, file=name,
                    producer=producer, reporter=args.worker_id)
        if self.daemon_events is not None:
            # lost-output revocation is a daemon-consequential decision:
            # put it on the fleet timeline too (leaf-lock stage)
            self.daemon_events("map_lost_output", task=tid,
                               producer=producer)
        # the producer demonstrably held committed state and vanished —
        # the direct analogue of the sweeper's attributed timeout
        # (WorkerHealth is a leaf lock, safe here like in the sweeper)
        if producer >= 0:
            window = self.worker_health.record_failure(producer)
            if window > 0:
                self.metrics.inc("workers_quarantined")
                _C_QUARANTINED.inc()
                self._event("quarantine", worker=producer,
                            window_s=round(window, 3))
        # free the REPORTING worker (the caller answers abort=True): its
        # reduce task re-enqueues now — NOT via a sweeper timeout later —
        # so the pool can run the re-executed map without dead time.  The
        # reporter takes no quarantine charge (it did nothing wrong).
        # Current-assignee reports only: a same-life straggler's report
        # re-enqueues the map above but must not yank the task from the
        # worker that legitimately holds it.
        rt = (self.reduce_tasks[args.task_id]
              if 0 <= args.task_id < len(self.reduce_tasks) else None)
        if rt is not None and rt.state is TaskState.IN_PROGRESS and (
            args.worker_id < 0 or rt.worker in (-1, args.worker_id)
        ):
            rt.state = TaskState.UNASSIGNED
            rt.worker = -1
            rt.stamped = False
            self._reduce_queue.append(args.task_id)
            self.metrics.inc("reduce_retries")
            self.metrics.inc("tasks_requeued")
            _C_REQUEUED.inc()
        self._cond.notify_all()
        return True

    # -------------------------------------------------------------- liveness
    def heartbeat(self, task_type: str, task_id: int, grace_s: float = 0.0,
                  args: rpc.HeartbeatArgs | None = None) -> None:
        """UpdateTimestamp (coordinator.go:176-182), plus the grace rider:
        a nonzero grace_s declares a silent phase (cold device compile) so
        the sweeper allows max(task_timeout_s, grace_s) before re-enqueue;
        any later stamp clears it.  Only IN_PROGRESS tasks accept stamps —
        a straggler's late heartbeat must not resurrect a task the sweeper
        already re-enqueued (its eventual completion is still absorbed
        idempotently).

        ``args`` is the full HeartbeatArgs when the transport has one
        (span-pipeline piggyback: buffered spans persist to the event log,
        the metrics snapshot lands in the worker table, and sent_at/rtt_s
        feed the per-worker ClockSync).  The positional form stays for
        direct callers/tests."""
        # receive time FIRST: the offset estimate prices the request
        # transit at rtt/2, so recv_at must be the POST arrival, not
        # arrival + span-persist + lock-wait (a systematic late bias the
        # EWMA could never average away)
        recv_at = time.time()
        if args is not None:
            self._persist_spans(args.spans, args.worker_id, args.spans_seq)
        with self._cond:
            if args is not None:
                self._worker_seen(args.worker_id, metrics=args.metrics)
                self._observe_clock(args, recv_at)
            table = self.map_tasks if task_type == "map" else self.reduce_tasks
            if 0 <= task_id < len(table):
                task = table[task_id]
                if task.state is TaskState.IN_PROGRESS:
                    g = max(0.0, float(grace_s))
                    if args is not None and g > 0 and task.grace_s != g:
                        # only on the transition: a retried grace stamp
                        # (response lost, re-POST) re-declares the same
                        # window and must not duplicate the event
                        self._event("grace_declared", task=task_id,
                                    type=task_type, worker=args.worker_id,
                                    grace_s=g)
                    task.heartbeat(grace_s=g)
                    if args is None or args.worker_id < 0 \
                            or args.worker_id == task.worker:
                        # stamped only by the CURRENT assignee (see
                        # reduce_next_file) — a straggler's pump must not
                        # charge the reassigned worker
                        task.stamped = True
                    self.metrics.inc("heartbeats")
        self._flush_events()

    def _sweep_loop(self) -> None:
        """Failure detector (coordinator.go:97-124): re-enqueue stale tasks."""
        import time as _time

        while True:
            requeued = False
            failed_workers: list[int] = []
            with self._cond:
                if self._stopped or self._done_locked():
                    return
                now = _time.monotonic()
                for task in self.map_tasks:
                    if (
                        task.state is TaskState.IN_PROGRESS
                        and now - task.timestamp
                        >= max(self.task_timeout_s, task.grace_s)
                    ):
                        log.warning("map task %d timed out; re-enqueueing", task.task_id)
                        if (
                            task.stamped or not self.worker_health.polled_since(
                                task.worker, task.timestamp
                            )
                        ) and not getattr(task, "fused_claim", False):
                            # charge only with evidence the worker HELD the
                            # task (a stamp) or is gone (no poll since the
                            # assignment) — an unstamped timeout from a
                            # worker that kept polling is a LOST REPLY, the
                            # network's fault, not the worker's.  Fused
                            # EXTRAS (claim_map_task) are never charged:
                            # K participant schedulers share ONE
                            # WorkerHealth, so one lost fused attempt
                            # would otherwise count as K consecutive
                            # failures and insta-quarantine; the PRIMARY
                            # assignment's timeout carries the one charge.
                            failed_workers.append(task.worker)
                        task.state = TaskState.UNASSIGNED
                        self._map_queue.append(task.task_id)
                        requeued = True
                        self.metrics.inc("map_retries")
                        self.metrics.inc("tasks_requeued")
                        _C_REQUEUED.inc()
                        self._event("task_timeout", type="map",
                                    task=task.task_id, attempt=task.attempts,
                                    worker=task.worker)
                        task.worker = -1
                        self._cond.notify_all()
                for task in self.reduce_tasks:
                    if (
                        task.state is TaskState.IN_PROGRESS
                        and now - task.timestamp
                        >= max(self.task_timeout_s, task.grace_s)
                    ):
                        log.warning("reduce task %d timed out; re-enqueueing", task.task_id)
                        if task.stamped or not self.worker_health.polled_since(
                            task.worker, task.timestamp
                        ):
                            failed_workers.append(task.worker)
                        task.state = TaskState.UNASSIGNED
                        self._reduce_queue.append(task.task_id)
                        requeued = True
                        self.metrics.inc("reduce_retries")
                        self.metrics.inc("tasks_requeued")
                        _C_REQUEUED.inc()
                        self._event("task_timeout", type="reduce",
                                    task=task.task_id, attempt=task.attempts,
                                    worker=task.worker)
                        task.worker = -1
                        self._cond.notify_all()
                # Attribute each charged timeout to the worker that held
                # the task (WorkerHealth is a leaf lock — safe under the
                # scheduler lock, and the quarantine verdict must land
                # before the re-enqueued task is handed back to the same
                # dark worker on the very next poll).  DEDUPED per sweep:
                # one worker going dark is ONE event however many tasks
                # it held — a FUSED attempt (round 13) parks K tasks on
                # one worker, and counting its single death K times
                # would quarantine on the first lost attempt.
                for wid in sorted(set(failed_workers)):
                    window = self.worker_health.record_failure(wid)
                    if window > 0:
                        log.warning(
                            "worker %d quarantined for %.1fs after %d "
                            "consecutive task timeouts", wid, window,
                            QUARANTINE_AFTER_FAILURES,
                        )
                        self.metrics.inc("workers_quarantined")
                        _C_QUARANTINED.inc()
                        self._event("quarantine", worker=wid,
                                    window_s=round(window, 3))
            self._flush_events()
            if requeued:
                self._notify_change()  # re-enqueued work is assignable again
            _time.sleep(self.sweep_interval_s)

    # ------------------------------------------------------------- predicates
    def _map_phase_done_locked(self) -> bool:
        return self._maps_completed == len(self.map_tasks)

    def map_phase_done(self) -> bool:
        with self._lock:
            return self._map_phase_done_locked()

    def _done_locked(self) -> bool:
        return (
            self._map_phase_done_locked()
            and self._reduces_completed == self.n_reduce
        )

    def done(self) -> bool:
        """Pure predicate — no teardown side effects (unlike coordinator.go:291-296)."""
        with self._lock:
            return self._done_locked()

    def backlog(self) -> dict:
        """Live demand snapshot for the service's elastic scale advice
        (round 16): ASSIGNABLE unassigned tasks (reduce tasks count only
        once the map phase is done — they cannot be handed out earlier),
        in-flight tasks, and the oldest in-flight heartbeat age (a
        growing age with idle capacity means stalled recovery, the
        other grow signal)."""
        now = time.monotonic()
        with self._lock:
            unassigned = sum(
                t.state is TaskState.UNASSIGNED for t in self.map_tasks
            )
            if self._map_phase_done_locked():
                unassigned += sum(
                    t.state is TaskState.UNASSIGNED
                    for t in self.reduce_tasks
                )
            in_flight = 0
            oldest = 0.0
            for table in (self.map_tasks, self.reduce_tasks):
                for t in table:
                    if t.state is TaskState.IN_PROGRESS:
                        in_flight += 1
                        oldest = max(oldest, now - t.timestamp)
            return {
                "unassigned": unassigned,
                "in_flight": in_flight,
                "oldest_inflight_age_s": round(oldest, 3),
            }

    def wait_done(self, timeout: Optional[float] = None) -> bool:
        with self._cond:
            return self._cond.wait_for(self._done_locked, timeout=timeout)

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()


class _Deadline:
    def __init__(self, timeout: float):
        import time as _time

        self._t = _time.monotonic
        self._deadline = self._t() + timeout

    def remaining(self) -> float:
        return self._deadline - self._t()

"""Coordinator task scheduler — the reference's semantics, without busy-polls.

Reproduces map_reduce/coordinator.go's behavior:

* one map task per input file, seeded up front (coordinator.go:329-333);
  reduce partitions 0..n_reduce-1 seeded alongside (coordinator.go:334-337);
* long-polling AssignTask: blocks until a map split is available; after the
  map phase completes, hands out reduce partitions (coordinator.go:43-95);
* file->task dedup so a re-enqueued file keeps its task id
  (coordinator.go:53-58);
* monotonically increasing worker ids allocated at assignment
  (coordinator.go:68,:86);
* streaming shuffle: ReduceNextFile blocks until the next intermediate file
  for that partition commits, or returns done once the map phase is over and
  the cursor is exhausted — so reducers run concurrently with maps
  (coordinator.go:159-174);
* heartbeats stamped at assignment and on every next-file fetch
  (coordinator.go:62,:82,:162); a background sweeper re-enqueues any
  in-progress task idle >= task_timeout_s (coordinator.go:97-124);
* idempotent completion: duplicate MapFinished/ReduceFinished short-circuit
  (coordinator.go:131-134);
* Done() when both phases complete (coordinator.go:276-299) — without the
  reference's side effect of tearing down connections inside the predicate.

Where the reference busy-polls (10 ms in AssignTask :89,:92, 50 ms in
ReduceNextFile :172, 1 s sweeper :122), this scheduler blocks on a single
condition variable and notifies on every state change.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Optional

from distributed_grep_tpu.runtime import rpc
from distributed_grep_tpu.runtime.journal import TaskJournal
from distributed_grep_tpu.runtime.types import MapTask, ReduceTask, TaskState
from distributed_grep_tpu.utils.logging import get_logger
from distributed_grep_tpu.utils.metrics import Metrics

log = get_logger("scheduler")


class Scheduler:
    """Transport-agnostic coordinator state machine (thread-safe)."""

    def __init__(
        self,
        files: list[str],
        n_reduce: int,
        task_timeout_s: float = 10.0,
        sweep_interval_s: float = 1.0,
        app_options: Optional[dict[str, Any]] = None,
        journal: Optional[TaskJournal] = None,
        resume_entries: Optional[list[dict]] = None,
        metrics: Optional[Metrics] = None,
        commit_resolver: Optional[Any] = None,
    ):
        self.n_reduce = n_reduce
        self.task_timeout_s = task_timeout_s
        self.sweep_interval_s = sweep_interval_s
        self.app_options = dict(app_options or {})
        self.journal = journal
        self.metrics = metrics or Metrics()
        # commit_resolver(kind, task_id) -> winning task commit record
        # payload or None (WorkDir.resolve_task_commit, runtime/store.py).
        # When a record exists it — not the finished-RPC args — is the unit
        # of truth for what a completed task produced: a re-executed
        # straggler whose late RPC races the sweeper's re-issue can then
        # never register parts its winning attempt did not commit.
        self.commit_resolver = commit_resolver

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)

        # Task tables (MapData/ReduceData, helper_types.go:150-161).
        self.map_tasks: list[MapTask] = [MapTask(i, f) for i, f in enumerate(files)]
        self.reduce_tasks: list[ReduceTask] = [ReduceTask(i) for i in range(n_reduce)]
        self.file_to_task: dict[str, int] = {f: i for i, f in enumerate(files)}

        # Work queues (the buffered channels, coordinator.go:329-337).
        self._map_queue: deque[int] = deque(range(len(files)))
        self._reduce_queue: deque[int] = deque(range(n_reduce))

        self._next_worker_id = 0  # safeInt.get_and_increment (helper_types.go:45-79)
        self._stopped = False
        # Incremental completion counters: COMPLETED is terminal (the
        # sweeper only re-enqueues IN_PROGRESS tasks), so counting at the
        # transitions replaces the per-event O(n) sweeps over the task
        # tables that made a 2,000-file `grep -r` job quadratic (round 5).
        self._maps_completed = 0
        self._reduces_completed = 0

        if resume_entries:
            self._replay(resume_entries)

        self._sweeper = threading.Thread(
            target=self._sweep_loop, name="failure-detector", daemon=True
        )
        self._sweeper.start()

    # ------------------------------------------------------------------ replay
    def _resolve_commit(self, kind: str, task_id: int):
        """The winning task commit record payload, or None (no resolver /
        no record).  Resolver failures count as 'no record' — the RPC-args
        path still works, so a broken commits dir degrades, not crashes."""
        if self.commit_resolver is None:
            return None
        try:
            return self.commit_resolver(kind, task_id)
        except Exception:  # noqa: BLE001 — degrade to RPC-args truth
            log.exception("commit record resolution failed for %s %d", kind, task_id)
            return None

    def _replay(self, entries: list[dict]) -> None:
        """Apply journal entries so a restarted coordinator skips done work."""
        for e in entries:
            if e.get("kind") == "map_done":
                tid = e["task_id"]
                if 0 <= tid < len(self.map_tasks):
                    t = self.map_tasks[tid]
                    if t.file != e.get("file"):
                        # Input list changed/reordered since the journal was
                        # written: this entry describes a different file, so
                        # the task must run again.
                        log.warning(
                            "journal entry for map task %d names %r but task file "
                            "is %r; ignoring entry",
                            tid,
                            e.get("file"),
                            t.file,
                        )
                        continue
                    parts = e.get("parts", [])
                    if e.get("has_record"):
                        # This completion was committed via a task commit
                        # record — re-resolve it as the unit of truth.  A
                        # journal entry whose record vanished is stale
                        # (someone swept the commits dir): re-run the task
                        # rather than trust unverifiable state.
                        record = self._resolve_commit("map", tid)
                        if record is None:
                            log.warning(
                                "journal says map task %d committed via record "
                                "but no valid record resolves; re-running", tid,
                            )
                            continue
                        # malformed record (no "parts"): keep the journal's
                        parts = record.get("parts", parts)
                    if t.state is not TaskState.COMPLETED:
                        t.state = TaskState.COMPLETED
                        self._register_map_outputs(tid, parts)
                        if tid in self._map_queue:
                            self._map_queue.remove(tid)
            elif e.get("kind") == "reduce_done":
                tid = e["task_id"]
                if 0 <= tid < len(self.reduce_tasks):
                    if e.get("has_record") and self._resolve_commit("reduce", tid) is None:
                        log.warning(
                            "journal says reduce task %d committed via record "
                            "but no valid record resolves; re-running", tid,
                        )
                        continue
                    t = self.reduce_tasks[tid]
                    t.state = TaskState.COMPLETED
                    if tid in self._reduce_queue:
                        self._reduce_queue.remove(tid)
        # one-time O(n) resync of the incremental counters after replay
        self._maps_completed = sum(
            t.state is TaskState.COMPLETED for t in self.map_tasks
        )
        self._reduces_completed = sum(
            t.state is TaskState.COMPLETED for t in self.reduce_tasks
        )
        log.info(
            "journal replay: %d map + %d reduce tasks already complete",
            self._maps_completed, self._reduces_completed,
        )

    # ----------------------------------------------------------------- assign
    def assign_task(self, args: rpc.AssignTaskArgs, timeout: float = 30.0) -> rpc.AssignTaskReply:
        """Long-poll for work.  Blocks until a task is available, the job is
        done (reply JOB_DONE), or `timeout` elapses (reply JOB_DONE only if
        actually done; otherwise an empty retry reply with task_id == -2)."""
        deadline = _Deadline(timeout)
        with self._cond:
            worker_id = args.worker_id
            if worker_id < 0:
                worker_id = self._next_worker_id
                self._next_worker_id += 1
            while True:
                if self._stopped or self._done_locked():
                    return rpc.AssignTaskReply(
                        assignment=rpc.Assignment.JOB_DONE, worker_id=worker_id
                    )
                while self._map_queue and (
                    self.map_tasks[self._map_queue[0]].state is not TaskState.UNASSIGNED
                ):
                    # Stale entry: the task timed out, was re-enqueued, and the
                    # original worker then completed it — never re-issue a
                    # COMPLETED (or already re-assigned) task.
                    self._map_queue.popleft()
                if self._map_queue:
                    tid = self._map_queue.popleft()
                    task = self.map_tasks[tid]
                    # file_to_task dedup keeps the same task id on re-issue
                    # (coordinator.go:53-58); queue entries are task ids here
                    # so the invariant holds by construction.
                    task.state = TaskState.IN_PROGRESS
                    task.heartbeat()
                    task.attempts += 1
                    self.metrics.inc("map_assigned")
                    log.debug("assign map task %d (%s) -> worker %d", tid, task.file, worker_id)
                    return rpc.AssignTaskReply(
                        assignment=rpc.Assignment.MAP,
                        filename=task.file,
                        task_id=tid,
                        n_reduce=self.n_reduce,
                        worker_id=worker_id,
                        app_options=self.app_options,
                        task_timeout_s=self.task_timeout_s,
                    )
                while self._reduce_queue and (
                    self.reduce_tasks[self._reduce_queue[0]].state is not TaskState.UNASSIGNED
                ):
                    self._reduce_queue.popleft()  # stale entry (see map queue above)
                if self._map_phase_done_locked() and self._reduce_queue:
                    tid = self._reduce_queue.popleft()
                    task = self.reduce_tasks[tid]
                    task.state = TaskState.IN_PROGRESS
                    task.heartbeat()
                    task.attempts += 1
                    self.metrics.inc("reduce_assigned")
                    log.debug("assign reduce task %d -> worker %d", tid, worker_id)
                    return rpc.AssignTaskReply(
                        assignment=rpc.Assignment.REDUCE,
                        task_id=tid,
                        n_reduce=self.n_reduce,
                        worker_id=worker_id,
                        app_options=self.app_options,
                        task_timeout_s=self.task_timeout_s,
                    )
                remaining = deadline.remaining()
                if remaining <= 0:
                    return rpc.AssignTaskReply(
                        assignment=rpc.Assignment.JOB_DONE if self._done_locked() else "retry",
                        task_id=-2,
                        worker_id=worker_id,
                    )
                self._cond.wait(timeout=min(remaining, self.sweep_interval_s))

    # ------------------------------------------------------------- completion
    def map_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply:
        """Idempotent map commit (coordinator.go:126-148)."""
        record = self._resolve_commit("map", args.task_id)
        with self._cond:
            task = self.map_tasks[args.task_id]
            if task.state is TaskState.COMPLETED:
                return rpc.TaskFinishedReply(ok=True)  # duplicate absorbed (:131-134)
            task.state = TaskState.COMPLETED
            self._maps_completed += 1
            # The task commit record (published before this RPC) is the
            # unit of truth for the produced partitions; the RPC args are
            # the fallback for transports without commit records — and for
            # a malformed record missing "parts" (the data plane accepts
            # any small JSON body; malformed degrades, never crashes).
            parts = args.produced_parts
            if record is not None and "parts" in record:
                parts = record["parts"]
            self._register_map_outputs(args.task_id, parts)
            self.metrics.inc("map_completed")
            if self.journal:
                self.journal.map_completed(
                    args.task_id, task.file, parts,
                    has_record=record is not None,
                )
            log.info(
                "map task %d done (%d/%d)",
                args.task_id, self._maps_completed, len(self.map_tasks),
            )
            self._cond.notify_all()
            return rpc.TaskFinishedReply(ok=True)

    def _register_map_outputs(self, map_task_id: int, produced_parts: list[int]) -> None:
        """Register committed intermediate files with their reduce partitions —
        only partitions the map actually produced (coordinator.go:139-141)."""
        for r in produced_parts:
            if 0 <= r < self.n_reduce:
                name = f"mr-{map_task_id}-{r}"
                if name not in self.reduce_tasks[r].task_files:
                    self.reduce_tasks[r].task_files.append(name)

    def reduce_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply:
        record = self._resolve_commit("reduce", args.task_id)
        with self._cond:
            task = self.reduce_tasks[args.task_id]
            if task.state is not TaskState.COMPLETED:
                task.state = TaskState.COMPLETED
                self._reduces_completed += 1
                self.metrics.inc("reduce_completed")
                if self.journal:
                    self.journal.reduce_completed(
                        args.task_id, has_record=record is not None
                    )
                log.info(
                    "reduce task %d done (%d/%d)",
                    args.task_id, self._reduces_completed, self.n_reduce,
                )
            self._cond.notify_all()
            return rpc.TaskFinishedReply(ok=True)

    # ------------------------------------------------------ streaming shuffle
    def reduce_next_file(
        self, args: rpc.ReduceNextFileArgs, timeout: float = 30.0
    ) -> rpc.ReduceNextFileReply:
        """The pipelined shuffle feed (coordinator.go:159-174): block until the
        reducer's next intermediate file exists, or the map phase is done and
        the cursor is exhausted (done=True).  Doubles as a heartbeat (:162)."""
        deadline = _Deadline(timeout)
        with self._cond:
            task = self.reduce_tasks[args.task_id]
            while True:
                task.heartbeat()
                if args.files_processed < len(task.task_files):
                    return rpc.ReduceNextFileReply(
                        next_file=task.task_files[args.files_processed], done=False
                    )
                if self._map_phase_done_locked():
                    return rpc.ReduceNextFileReply(done=True)
                remaining = deadline.remaining()
                if remaining <= 0:
                    # Not done — client should re-poll (long-poll window expired).
                    return rpc.ReduceNextFileReply(next_file="", done=False)
                self._cond.wait(timeout=min(remaining, self.sweep_interval_s))

    # -------------------------------------------------------------- liveness
    def heartbeat(self, task_type: str, task_id: int,
                  grace_s: float = 0.0) -> None:
        """UpdateTimestamp (coordinator.go:176-182), plus the grace rider:
        a nonzero grace_s declares a silent phase (cold device compile) so
        the sweeper allows max(task_timeout_s, grace_s) before re-enqueue;
        any later stamp clears it.  Only IN_PROGRESS tasks accept stamps —
        a straggler's late heartbeat must not resurrect a task the sweeper
        already re-enqueued (its eventual completion is still absorbed
        idempotently)."""
        with self._cond:
            table = self.map_tasks if task_type == "map" else self.reduce_tasks
            if 0 <= task_id < len(table):
                task = table[task_id]
                if task.state is TaskState.IN_PROGRESS:
                    task.heartbeat(grace_s=max(0.0, float(grace_s)))
                    self.metrics.inc("heartbeats")

    def _sweep_loop(self) -> None:
        """Failure detector (coordinator.go:97-124): re-enqueue stale tasks."""
        import time as _time

        while True:
            with self._cond:
                if self._stopped or self._done_locked():
                    return
                now = _time.monotonic()
                for task in self.map_tasks:
                    if (
                        task.state is TaskState.IN_PROGRESS
                        and now - task.timestamp
                        >= max(self.task_timeout_s, task.grace_s)
                    ):
                        log.warning("map task %d timed out; re-enqueueing", task.task_id)
                        task.state = TaskState.UNASSIGNED
                        self._map_queue.append(task.task_id)
                        self.metrics.inc("map_retries")
                        self._cond.notify_all()
                for task in self.reduce_tasks:
                    if (
                        task.state is TaskState.IN_PROGRESS
                        and now - task.timestamp
                        >= max(self.task_timeout_s, task.grace_s)
                    ):
                        log.warning("reduce task %d timed out; re-enqueueing", task.task_id)
                        task.state = TaskState.UNASSIGNED
                        self._reduce_queue.append(task.task_id)
                        self.metrics.inc("reduce_retries")
                        self._cond.notify_all()
            _time.sleep(self.sweep_interval_s)

    # ------------------------------------------------------------- predicates
    def _map_phase_done_locked(self) -> bool:
        return self._maps_completed == len(self.map_tasks)

    def map_phase_done(self) -> bool:
        with self._lock:
            return self._map_phase_done_locked()

    def _done_locked(self) -> bool:
        return (
            self._map_phase_done_locked()
            and self._reduces_completed == self.n_reduce
        )

    def done(self) -> bool:
        """Pure predicate — no teardown side effects (unlike coordinator.go:291-296)."""
        with self._lock:
            return self._done_locked()

    def wait_done(self, timeout: Optional[float] = None) -> bool:
        with self._cond:
            return self._cond.wait_for(self._done_locked, timeout=timeout)

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()


class _Deadline:
    def __init__(self, timeout: float):
        import time as _time

        self._t = _time.monotonic
        self._deadline = self._t() + timeout

    def remaining(self) -> float:
        return self._deadline - self._t()

"""External sort-merge grouping: the reduce phase with bounded memory.

The reference decodes every record of a reduce partition into RAM, sorts,
and groups (map_reduce/worker.go:146-176, reduceDistinctKeys at :22-43) —
an OOM for a hot partition of the north star's 100 GB corpus.  Here records
accumulate only up to a memory cap; overflow spills as a *sorted run* to
local disk (the shuffle wire format, runtime/shuffle.py), and grouping is a
lazy k-way heap merge over the runs plus the final in-memory batch.  The
map side solved its version of this with newline-aligned chunk streaming
(ops/engine.py scan_file); this is the reduce-side counterpart.

Determinism contract (matches the in-memory path): keys stream in sorted
order; within one key, values keep their arrival order — the merge
tie-breaks on (run index, sequence within run), and runs spill in arrival
order.

Hot-key note: ``reduce_fn(key, values)`` receives a list per the reference
contract, so one key's values are still materialized.  Applications that
fold associatively can expose ``reduce_stream_fn(key, values_iter)`` to
stay O(1) per key (apps/wordcount.py does); the worker prefers it when
present.
"""

from __future__ import annotations

import heapq
import json
import shutil
import tempfile
from itertools import groupby
from pathlib import Path
from typing import Iterable, Iterator

from distributed_grep_tpu.apps.base import KeyValue, sort_by_key
from distributed_grep_tpu.runtime import shuffle

# Rough per-record bookkeeping overhead (tuple + two str objects) used for
# the memory estimate; exactness doesn't matter, boundedness does.
_RECORD_OVERHEAD = 120


class ExternalReducer:
    """Accumulate KeyValue records under a memory cap; group-reduce by
    streaming a sorted merge of spilled runs."""

    def __init__(self, memory_limit_bytes: int = 128 << 20,
                 spill_dir: str | None = None):
        """``spill_dir``: where runs land.  Pass a real-disk directory in
        production — the system temp dir is often RAM-backed tmpfs, which
        would defeat the memory cap (the worker passes one, worker.py)."""
        if memory_limit_bytes <= 0:
            raise ValueError("memory_limit_bytes must be positive")
        self.memory_limit = memory_limit_bytes
        self._spill_parent = spill_dir
        self._tmp: str | None = None
        self._mem: list[KeyValue] = []
        self._mem_bytes = 0
        self._runs: list[Path] = []

    @property
    def spill_count(self) -> int:
        return len(self._runs)

    def add_many(self, records: Iterable[KeyValue]) -> None:
        for kv in records:
            self._mem.append(kv)
            self._mem_bytes += len(kv.key) + len(kv.value) + _RECORD_OVERHEAD
            if self._mem_bytes >= self.memory_limit:
                self._spill()

    def _spill(self) -> None:
        if not self._mem:
            return
        if self._tmp is None:
            self._tmp = tempfile.mkdtemp(prefix="dgrep-reduce-",
                                         dir=self._spill_parent)
        run = Path(self._tmp) / f"run-{len(self._runs)}"
        recs = sort_by_key(self._mem)
        with open(run, "wb") as f:
            # batched encode: the whole run as one string+bytes would
            # transiently ~triple memory right when the cap was hit
            for i in range(0, len(recs), 4096):
                f.write(shuffle.encode_records(recs[i : i + 4096]))
        self._runs.append(run)
        self._mem = []
        self._mem_bytes = 0

    @staticmethod
    def _iter_run(path: Path) -> Iterator[tuple[str, str]]:
        # Text-mode line iteration is safe here: the wire format JSON-escapes
        # \r and \n inside strings, so the only newlines in the file are the
        # record separators (universal-newline translation has nothing to
        # translate; U+2028/U+2029 are not file line breaks).
        with open(path, encoding="utf-8", errors="surrogateescape",
                  newline="\n") as f:
            for line in f:
                line = line.rstrip("\n")
                if line:
                    k, v = json.loads(line)
                    yield k, v

    def merged(self) -> Iterator[tuple[str, str]]:
        """All records in (key, run index, sequence) order — i.e. key-sorted,
        arrival-stable within a key.  Public seam: ``reduce()`` groups over
        it, and JobResult.iter_results_sorted re-sorts collation output
        through it (the sorter doubles as a general bounded-memory
        external sort)."""
        def tagged(stream, idx):
            # idx must bind per-stream (a bare generator expression would
            # late-bind the loop variable and break the run tie-break)
            return ((k, idx, i, v) for i, (k, v) in enumerate(stream))

        streams = [tagged(self._iter_run(run), idx)
                   for idx, run in enumerate(self._runs)]
        tail = ((kv.key, kv.value) for kv in sort_by_key(self._mem))
        streams.append(tagged(tail, len(self._runs)))
        for k, _, _, v in heapq.merge(*streams):
            yield k, v

    def reduce(self, reduce_fn, stream_fn=None) -> Iterator[tuple[str, str]]:
        """Yield (key, reduced_value) in sorted key order, streaming.

        ``stream_fn(key, values_iterator)`` — when the application provides
        one — is preferred over ``reduce_fn(key, values_list)``: it never
        materializes a hot key's value list.
        """
        for k, grp in groupby(self.merged(), key=lambda t: t[0]):
            vals = (v for _, v in grp)
            yield (k, stream_fn(k, vals)) if stream_fn is not None else (
                k, reduce_fn(k, list(vals))
            )

    def close(self) -> None:
        if self._tmp is not None:
            shutil.rmtree(self._tmp, ignore_errors=True)
            self._tmp = None
        self._mem = []
        self._runs = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

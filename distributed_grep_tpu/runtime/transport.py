"""Transports: how a worker reaches the coordinator and the data plane.

The reference splits control (Go net/rpc over HTTP, coordinator.go:184-193)
from data (SSH/SFTP file copies through the coordinator host,
coordinator.go:195-265).  Here the same split is a Protocol with two
implementations: LocalTransport (in-process scheduler + shared work dir —
the single-process spine and the shared-FS cluster mode) and HttpTransport
(runtime/http_transport.py — long-poll control plane + HTTP data plane for
multi-process/multi-host without a shared FS).
"""

from __future__ import annotations

from typing import Protocol

from distributed_grep_tpu.runtime import rpc
from distributed_grep_tpu.runtime.scheduler import Scheduler
from distributed_grep_tpu.utils.io import WorkDir, resolve_input_path


class Transport(Protocol):
    # --- control plane (the four verbs of rpc.go) --------------------------
    def assign_task(self, args: rpc.AssignTaskArgs) -> rpc.AssignTaskReply: ...
    def map_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply: ...
    def reduce_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply: ...
    def reduce_next_file(self, args: rpc.ReduceNextFileArgs) -> rpc.ReduceNextFileReply: ...
    # Optional: heartbeat(args) — advisory mid-task liveness stamp (never
    # raises; the worker checks hasattr before wiring progress callbacks).

    # --- data plane (what SFTP push/pull becomes) --------------------------
    def read_input(self, filename: str) -> bytes: ...
    def write_intermediate(self, name: str, data: bytes) -> None: ...
    def read_intermediate(self, name: str) -> bytes: ...
    def write_output(self, name: str, data: bytes) -> None: ...
    # Optional: write_output_from_file(name, path) — commit a local file as
    # an output without loading it whole (the streaming-reduce counterpart
    # of write_output).  The worker falls back to write_output when a
    # transport lacks it (runtime/worker.py).
    # Optional: publish_task_commit(kind, task_id, attempt, payload) —
    # publish the per-task commit record (runtime/store.py) after all of a
    # task's blobs are durable and before the finished RPC.  The worker
    # skips it on transports without one (custom test transports keep the
    # RPC args as the registration source).


class LocalTransport:
    """Direct scheduler calls + shared-filesystem data plane."""

    # data-plane ops resolve in microseconds: the worker skips its
    # download-leg liveness pump for this transport (worker.py)
    is_local = True

    def __init__(self, scheduler: Scheduler, workdir: WorkDir,
                 rpc_timeout_s: float = 30.0, store=None):
        self.scheduler = scheduler
        self.workdir = workdir
        self.rpc_timeout_s = rpc_timeout_s
        # store override: fault-injection wraps THIS worker's commit path
        # without touching the shared workdir store other workers use
        self.store = store if store is not None else workdir.store

    def assign_task(self, args: rpc.AssignTaskArgs) -> rpc.AssignTaskReply:
        return self.scheduler.assign_task(args, timeout=self.rpc_timeout_s)

    def map_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply:
        return self.scheduler.map_finished(args)

    def reduce_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply:
        return self.scheduler.reduce_finished(args)

    def reduce_next_file(self, args: rpc.ReduceNextFileArgs) -> rpc.ReduceNextFileReply:
        return self.scheduler.reduce_next_file(args, timeout=self.rpc_timeout_s)

    def heartbeat(self, args: rpc.HeartbeatArgs) -> float:
        # full args through: the span-pipeline piggyback (buffered spans,
        # metrics snapshot, clock-sync observations) rides the same stamp.
        # Returns an RTT sample like HttpTransport (the worker treats a
        # non-float return as "no valid sample") — 0.0 here, NOT the
        # handler duration: same process, same clock, zero transit; timing
        # the synchronous call would fold event-log flush time into the
        # offset estimate and shift the worker's trace row negative.
        self.scheduler.heartbeat(
            args.task_type, args.task_id, grace_s=args.grace_s, args=args
        )
        return 0.0

    def read_input(self, filename: str) -> bytes:
        return resolve_input_path(filename, self.workdir).read_bytes()

    def read_input_path(self, filename: str):
        """(local_path, is_temp) — streaming apps (map_path_fn) read the
        file themselves in bounded chunks instead of receiving all bytes.
        Shared-FS transport: the original path, nothing to clean up."""
        return resolve_input_path(filename, self.workdir), False

    def write_intermediate(self, name: str, data: bytes) -> None:
        self.store.put(self.workdir.root / "intermediate" / name, data)

    def read_intermediate(self, name: str) -> bytes:
        return self.store.get(self.workdir.root / "intermediate" / name)

    def write_output(self, name: str, data: bytes) -> None:
        self.store.put(self.workdir.root / "out" / name, data)

    def write_output_from_file(self, name: str, path: str) -> None:
        # the worker donates its spool (it only ever unlinks leftovers):
        # a rename-capable store commits it zero-copy (round 8)
        self.store.put_from_file(
            self.workdir.root / "out" / name, path, consume=True
        )

    def publish_task_commit(self, kind: str, task_id: int, attempt: str,
                            payload: dict) -> None:
        self.store.commit_task(
            self.workdir.commits_dir(), kind, task_id, attempt, payload
        )

"""Query-result cache — the fourth warm tier (round 20).

Model cache answers "same pattern", corpus cache "same data", shard index
"cannot match"; this tier answers "same pattern over same data" with the
stored RESULT: a repeated query over unchanged inputs is a stat walk plus
a cache read, not a scan.  Results are stored PER MAP SPLIT — the split's
final output records together with its content identity — so invalidation
is per-shard: when one file of a thousand drifts, only its split rescans
(the incremental re-query) and the merge with the surviving cached splits
is byte-identical to a full scan (the unique-(file, line) keys make any
k-way ``fileline_sorted`` merge partition-independent).

Key = ``(fusion_key(config), query_spec(options))`` x split identity.
``fusion_key`` already canonicalizes application + every non-query app
option + the split-planning window, so two configs share cache entries
exactly when their split plans align and their per-record semantics
agree; folding the query spec back in is what distinguishes tenants —
the fusion planner may RUN two queries in one dispatch, but their
RESULTS are never interchangeable.  The split identity is the CorpusCache
validator tuple (realpath, size, mtime_ns, inode — fresh-stat
revalidated; drift evicts; stale results are NEVER served).

Persistence rides the IndexStore mechanics (index/store.py): one file
per (query, split) under ``<work_root>/results/``, content-hash
filenames, JSON header + raw record bytes, tmp + ``os.replace``, NO
fsync (a lost entry rescans).  On top of that: whole-entry LRU under the
``DGREP_RESULT_BYTES`` budget (mtime is the recency clock — loads touch;
an entry larger than the whole budget is DECLINED, never evicting
smaller tenants — the CorpusCache put_segments rule).

Pure Python, no ops imports — eligibility and planning run on the
daemon's control plane (the runtime/fusion.py rule), and every stat or
store I/O here runs in caller context with no service lock held
(analyze: locked-blocking).

Knobs (registered in analysis/knobs.py, owned here):

* ``DGREP_RESULT_CACHE`` — 0/false disables the tier entirely (a true
  no-op: no ``results/`` dir, no /status key, byte-identical behavior).
  The daemon defaults it ON; one-shot CLI jobs never consult the tier
  at all (their temp work dirs make reuse meaningless).
* ``DGREP_RESULT_BYTES`` — on-disk budget for ``results/`` (default
  256 MB); 0 disables like DGREP_RESULT_CACHE=0.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from pathlib import Path

from distributed_grep_tpu.runtime import fusion as fusion_mod
from distributed_grep_tpu.runtime.job import parse_grep_key_bytes

_VERSION = 1
DEFAULT_RESULT_BYTES = 256 << 20


def env_result_cache(default: bool = True) -> bool:
    """Result-tier switch — the ONE parser of DGREP_RESULT_CACHE
    (fusion's env_service_fuse policy: "0"/"false"/"no" = off)."""
    raw = os.environ.get("DGREP_RESULT_CACHE")
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in ("0", "false", "no")


def env_result_bytes(default: int = DEFAULT_RESULT_BYTES) -> int:
    """Result-store byte budget — the ONE parser of DGREP_RESULT_BYTES
    (malformed keeps the default, env_batch_bytes' shrug-off policy;
    negatives clamp to 0 = disabled)."""
    raw = os.environ.get("DGREP_RESULT_BYTES")
    if raw is None or raw == "":
        return default
    try:
        return max(0, int(raw))
    except ValueError:
        return default


def result_key(config) -> tuple | None:
    """Cache key for a JobConfig's query half, or None when this job's
    results must never be cached.  Eligibility mirrors fusion
    (grep_tpu, print mode, no approx/mesh/backrefs/empty patterns —
    fusion_key refuses all of those) narrowed further: standing queries
    have no terminal result, and ``-v`` rides the _UNPRUNABLE_OPTIONS
    rationale — its output is the complement (every line of a zero-match
    file), so entries would be corpus-sized and defeat the budget."""
    if getattr(config, "follow", False):
        return None
    fkey = fusion_mod.fusion_key(config)
    if fkey is None:
        return None
    opts = config.effective_app_options()
    if opts.get("invert"):
        return None
    qspec = fusion_mod.query_spec(opts)
    if qspec is None:  # unreachable past fusion_key; belt-and-braces
        return None
    return (fkey, qspec)


def _canon(obj):
    """Tuples -> lists (the JSON round-trip shape) and bytes -> str via
    surrogateescape, recursively — stored headers must compare equal to
    a live key's fields after one json round trip."""
    if isinstance(obj, (list, tuple)):
        return [_canon(x) for x in obj]
    if isinstance(obj, bytes):
        return obj.decode("utf-8", "surrogateescape")
    return obj


class ResultKey:
    """One (query, split) cache address.  ``identity`` names the file
    (query key + the member GIVEN names + their realpaths — stable
    across content drift, so a drifted lookup maps to the SAME entry
    and evicts it); ``validators`` is the full split identity the load
    revalidates.  The given names are load-bearing: stored records
    carry the publishing job's path spellings (fusion's symlinked
    tenants keep per-job names), so a submit naming the same content
    through an alias must MISS — a realpath-only identity would serve
    it records labeled with the other tenant's paths."""

    __slots__ = ("identity", "validators")

    def __init__(self, query_key: tuple, split, split_ident: tuple):
        members = split if isinstance(split, (list, tuple)) else [split]
        self.identity = (
            _canon(query_key),
            [os.fsdecode(os.fspath(m)) for m in members],
            [m[0] for m in split_ident],
        )
        self.validators = split_ident


class ResultStore:
    """IndexStore mechanics + LRU byte budget.  All I/O is best-effort
    and runs in caller context with no lock held; a full disk or a lost
    entry degrades warm answering, never correctness."""

    def __init__(self, root):
        self.root = Path(root)
        self._made = False
        # lockless telemetry (single-writer daemon planning thread;
        # approximate reads are fine)
        self.stale_evictions = 0
        self.lru_evictions = 0
        # sweep tmp files torn by a crash between the tmp write and
        # os.replace — _evict only accounts *.res, so they would leak
        # unbounded across daemon lifetimes.  Construction implies
        # work-root ownership (the lease in HA mode), so no live
        # writer's tmp can be on disk here.
        try:
            with os.scandir(self.root) as it:
                for e in it:
                    if e.name.endswith(".tmp"):
                        try:
                            os.unlink(e.path)
                        except OSError:
                            pass
        except OSError:
            pass

    def _path_for(self, identity) -> Path:
        blob = json.dumps(_canon(identity), ensure_ascii=True,
                          separators=(",", ":"))
        h = hashlib.sha256(blob.encode("utf-8", "surrogatepass")).hexdigest()
        return self.root / f"{h[:40]}.res"

    def load(self, key: ResultKey) -> bytes | None:
        """The stored split result for ``key``, or None.  A record whose
        validators disagree with the key's fresh stat is STALE: deleted
        (best-effort) and never served.  A hit touches mtime — the LRU
        recency clock."""
        p = self._path_for(key.identity)
        try:
            with open(p, "rb") as f:
                header = json.loads(f.readline())
                blob = f.read()
        except (OSError, ValueError):
            return None
        if (
            header.get("v") != _VERSION
            or header.get("identity") != _canon(key.identity)
            or len(blob) != header.get("m")
        ):
            return None
        if header.get("validators") != _canon(key.validators):
            self.stale_evictions += 1
            try:
                os.unlink(p)  # stat drift: evict the stale record
            except OSError:
                pass
            return None
        try:
            os.utime(p)
        except OSError:
            pass
        return blob

    def save(self, key: ResultKey, records: bytes) -> bool:
        """Atomically persist one split's result records, then enforce
        the byte budget (oldest-mtime whole-entry eviction, never the
        entry just written).  Entries larger than the whole budget are
        declined outright — publishing one would LRU-wipe every smaller
        tenant for a result that can never be served warm again."""
        budget = env_result_bytes()
        if budget <= 0 or len(records) > budget:
            return False
        p = self._path_for(key.identity)
        header = json.dumps({
            "v": _VERSION,
            "identity": _canon(key.identity),
            "validators": _canon(key.validators),
            "m": len(records),
        }, ensure_ascii=True, separators=(",", ":"))
        tmp = p.with_name(
            f".{p.name}.{os.getpid()}.{threading.get_ident()}.tmp"
        )
        try:
            if not self._made:
                self.root.mkdir(parents=True, exist_ok=True)
                self._made = True
            with open(tmp, "wb") as f:
                f.write(header.encode("utf-8", "surrogatepass"))
                f.write(b"\n")
                f.write(records)
            os.replace(tmp, p)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        self._evict(budget, keep=p)
        return True

    def _evict(self, budget: int, keep: Path) -> None:
        """Whole-entry LRU: drop oldest-mtime entries until the store
        fits the budget.  Best-effort — a racing unlink just means the
        entry was already gone."""
        rows = []
        total = 0
        try:
            with os.scandir(self.root) as it:
                for e in it:
                    if not e.name.endswith(".res"):
                        continue
                    try:
                        st = e.stat()
                    except OSError:
                        continue
                    rows.append((st.st_mtime_ns, st.st_size, e.path))
                    total += st.st_size
        except OSError:
            return
        if total <= budget:
            return
        keep_s = os.fspath(keep)
        for _mtime, size, path in sorted(rows):
            if total <= budget:
                break
            if path == keep_s:
                continue
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= size
            self.lru_evictions += 1


class ResultPlan:
    """One job's submit-time cache verdicts: which planned splits answer
    from cache (original index + record blob) and which must scan
    (``remaining``, with their submit-time identities for publication
    revalidation).  Built OUTSIDE the service lock (stat + store I/O)."""

    __slots__ = ("query_key", "splits", "cached", "remaining",
                 "remaining_identities", "bytes_unscanned")

    def __init__(self, query_key):
        self.query_key = query_key
        self.splits: list = []
        self.cached: list[tuple[int, bytes]] = []
        self.remaining: list = []
        self.remaining_identities: list = []
        self.bytes_unscanned = 0

    @property
    def full(self) -> bool:
        return bool(self.splits) and not self.remaining

    @property
    def splits_reused(self) -> int:
        return len(self.cached)


def plan_lookup(store: ResultStore, query_key: tuple,
                splits: list) -> ResultPlan:
    """Look every planned split up in the store with a FRESH stat per
    member (drifted entries evict inside load()).  Splits without a
    stable identity (unstattable, oversize) always scan and never
    publish."""
    plan = ResultPlan(query_key)
    plan.splits = list(splits)
    for i, split in enumerate(splits):
        ident = fusion_mod.split_identity(split)
        blob = None
        if ident is not None:
            blob = store.load(ResultKey(query_key, split, ident))
        if blob is not None:
            plan.cached.append((i, blob))
            plan.bytes_unscanned += fusion_mod.split_n_bytes(ident)
        else:
            plan.remaining.append(split)
            plan.remaining_identities.append(ident)
    return plan


def bucket_records(output_paths, splits) -> list[bytes] | None:
    """Partition a finished job's committed output records back into
    per-split blobs, sorted by (file, line) — each blob is then itself a
    valid ``fileline_sorted`` stream for the k-way merge.  Returns None
    when any record cannot be attributed (unparseable key, or a path no
    split owns — a custom record shape): publication is all-or-nothing
    per job, a wrong attribution must never poison an entry.  Paths
    order by surrogateescape CODEPOINTS (the merge's se_cmp contract),
    not raw bytes."""
    owner: dict[bytes, int] = {}
    for i, split in enumerate(splits):
        members = split if isinstance(split, (list, tuple)) else [split]
        for m in members:
            key = os.fsencode(os.fspath(m))
            if key in owner:
                # a repeated member (the same file listed twice in
                # input_files) makes attribution ambiguous — records
                # would all land on the last split, and two
                # same-identity splits would overwrite each other's
                # store entry; publish nothing
                return None
            owner[key] = i
    buckets: list[list] = [[] for _ in splits]
    for path in output_paths:
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return None
        for line in data.splitlines(keepends=True):
            if not line.rstrip(b"\n"):
                continue
            key = line.split(b"\t", 1)[0]
            parsed = parse_grep_key_bytes(key)
            if parsed is None:
                return None
            path_b, lineno = parsed
            i = owner.get(path_b)
            if i is None:
                return None
            buckets[i].append(
                (path_b.decode("utf-8", "surrogateescape"), lineno, line)
            )
    out = []
    for rows in buckets:
        rows.sort(key=lambda t: (t[0], t[1]))
        out.append(b"".join(t[2] for t in rows))
    return out

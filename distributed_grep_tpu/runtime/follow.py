"""Streaming tier (round 17): standing queries over live-append inputs.

The batch runtime answers "what matched" for a corpus frozen at submit
time; the workload a production grep service actually carries is the log
tail — files that GROW while the query is standing.  This module makes
live-append a first-class regime:

* ``FollowScanner`` — per-file durable cursors (byte offset of the first
  INCOMPLETE line, always a line start) + suffix scans through
  ``GrepEngine.scan_file_suffix``: each wake scans ONLY the appended
  complete-line suffix; the partial tail line is carried and re-scanned
  extended on the next wake, so emitted lines are byte-identical to a
  one-shot scan over the final file state (the oracle every test pins).
  Exactness at every append boundary rides the repo's load-bearing
  invariant — the DFA '\\n'-column==start reset means a buffer that
  begins at a line start and ends at a line boundary scans exactly like
  the same lines inside a whole-file scan, on every kernel family.
  Truncation/replacement is detected via the validator-tuple rule (size
  below the cursor, or a changed inode — the cp -p + mv case) and
  answers with a ``reset`` record + a full rescan from offset 0.
* ``FollowLog`` — the durable half (TaskJournal mechanics: fsync per
  line, torn tail truncated on reopen): ONE json line per (wake, file)
  carrying the new cursor AND the records it emitted, atomically — a
  daemon restart resumes every standing query from its cursors with no
  duplicate and no lost line (a torn wake line never advanced the
  cursor, so its records simply re-emit; a complete line advanced it
  exactly once).
* ``StreamRing`` — the bounded per-job subscriber buffer behind
  ``GET /jobs/<id>/stream``: the scan loop publishes and NEVER blocks;
  past ``DGREP_STREAM_BUFFER`` bytes the oldest records shed (counted in
  ``stream_dropped_records``) and a consumer whose cursor fell behind
  receives an explicit ``dropped`` count, then continues from the
  oldest retained record.
* ``FollowRunner`` — one daemon-side standing query: engine build
  (ops.engine.cached_engine — imported lazily; this module stays
  importable without the ops stack, like runtime/fusion), wake loop at
  the ``DGREP_FOLLOW_POLL_S`` cadence, journal-before-publish ordering
  (durability before visibility, the registry's submit contract).

Count-only standing queries (``count_only``/``presence_only`` app
options — the CLI's -c/-l/-q) never materialize lines: wake records
carry per-file count deltas, so the match-dense worst case is a
bandwidth-bound counter update.

The follow path never consults the shard index: a stale trigram summary
can therefore never prune a standing query (and the batch entries'
lookups revalidate fresh stats anyway — an append IS stat drift).

Fused follow tier (round 21): ``FollowGroupRegistry``/``FollowGroup``
cluster standing queries whose configs share a fusion-eligible
``runtime/fusion.follow_fusion_key`` — same watched-input realpath set,
same non-query options, a union-hostable query — under ONE shared
per-file cursor and ONE wake loop (cadence = the tightest member's
poll_s): each wake runs one suffix read + one union scan
(``ops/fuse.FusedScanner.scan_suffix``) and fans each member's exact
confirmed result into that member's OWN FollowLog + StreamRing, so
per-job durability/replay/reconnect are untouched while reads, scans,
and engine state stop scaling with K.  Members joining a live group
catch up solo (on the group thread, byte-budgeted so the capped read
lands exactly on the group cursor) before fusing; any fused-leg
failure, per-file truncation/inode reset, or FuseError falls members
back to their pre-round-21 solo runner — fusion is never a correctness
dependency.  ``DGREP_FOLLOW_FUSE=0`` is a TRUE no-op: no registry, no
/status group view, solo runners byte-identical to round 17.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from pathlib import Path

from distributed_grep_tpu.runtime.journal import TaskJournal
from distributed_grep_tpu.utils import lockdep
from distributed_grep_tpu.utils.logging import get_logger

log = get_logger("follow")

DEFAULT_FOLLOW_POLL_S = 0.5
DEFAULT_STREAM_BUFFER = 4 << 20

# Per-wake suffix read cap: one wake scans at most this much appended
# data (bounded memory — the catch-up over a huge existing file proceeds
# cap-sized wake by wake; the cursor simply advances in steps).
MAX_WAKE_BYTES = 64 << 20


def env_follow_poll_s(default: float = DEFAULT_FOLLOW_POLL_S) -> float:
    """Standing-query wake cadence — the ONE parser of
    DGREP_FOLLOW_POLL_S (operator override; malformed or <= 0 keeps the
    default, the env_batch_bytes shrug-off policy)."""
    raw = os.environ.get("DGREP_FOLLOW_POLL_S")
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def env_stream_buffer(default: int = DEFAULT_STREAM_BUFFER) -> int:
    """Per-subscriber stream buffer byte cap — the ONE parser of
    DGREP_STREAM_BUFFER (a slow consumer sheds oldest-first past it;
    malformed or < 1 keeps the default)."""
    raw = os.environ.get("DGREP_STREAM_BUFFER")
    if raw is None or raw == "":
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def env_follow_fuse(default: bool = True) -> bool:
    """Fused-follow switch — the ONE parser of DGREP_FOLLOW_FUSE.  On by
    default; "0"/"false"/"no" disables the group registry entirely (a
    TRUE no-op: runners start their pre-round-21 solo threads, /status
    carries no group view, the fused counters never tick)."""
    raw = os.environ.get("DGREP_FOLLOW_FUSE")
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in ("0", "false", "no")


# ------------------------------------------------------ module telemetry
# Process-global follow counters, the fusion_counters contract: leaf
# lock, nonzero-only reads, merged into engine.stats (ops/engine.scan
# tail), the worker heartbeat piggyback (worker._engine_cache_counters),
# and the service /status "follow" view — all sys.modules-gated so
# follow-free processes never import this module just to report nothing.
_stats_lock = lockdep.make_lock("follow-stats")
_stats = {
    "follow_wakes": 0,
    "suffix_bytes_scanned": 0,
    "stream_dropped_records": 0,
}


def _count(name: str, n: int = 1) -> None:
    with _stats_lock:
        _stats[name] += n


def follow_counters() -> dict:
    """Copy of the follow counters, or {} when never touched (the
    nonzero-only piggyback/stats contract)."""
    with _stats_lock:
        if not any(_stats.values()):
            return {}
        return dict(_stats)


def follow_counters_clear() -> None:
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0


# Fused-follow counters (round 21): same contract, SEPARATE dict so the
# DGREP_FOLLOW_FUSE=0 no-op pin stays byte-exact — solo runners touch
# only the base dict above.  follow_fused_queries = standing queries
# adopted into groups; follow_fused_wakes = group wakes with news that
# served >= 2 fused members; follow_suffix_bytes_saved = suffix bytes
# the co-members did NOT re-read/re-scan ((K_live - 1) x consumed).
_fused_stats_lock = lockdep.make_lock("follow-fused-stats")
_fused_stats = {
    "follow_fused_queries": 0,
    "follow_fused_wakes": 0,
    "follow_suffix_bytes_saved": 0,
}


def _count_fused(name: str, n: int = 1) -> None:
    with _fused_stats_lock:
        _fused_stats[name] += n


def follow_fused_counters() -> dict:
    """Copy of the fused-follow counters, or {} when never touched."""
    with _fused_stats_lock:
        if not any(_fused_stats.values()):
            return {}
        return dict(_fused_stats)


def follow_fused_counters_clear() -> None:
    with _fused_stats_lock:
        for k in _fused_stats:
            _fused_stats[k] = 0


# ------------------------------------------------------------- cursors
@dataclass
class FileCursor:
    """Durable per-file scan position: ``offset`` is the byte offset of
    the first INCOMPLETE line (always a line start — the suffix-scan
    exactness invariant), ``line`` the 1-based line number at that
    offset.  ``ino`` anchors the validator-tuple truncation rule."""

    path: str
    offset: int = 0
    line: int = 1
    ino: int = -1
    emitted: int = 0  # selected lines so far (exit codes, -c display)
    done: bool = False  # presence settled (presence_only queries)
    # TRANSIENT (not journaled — a restart just rescans once): the stat
    # size of the last no-progress scan, so an unterminated tail is not
    # re-read from disk every wake until the file actually grows
    seen: int = -1

    def state(self) -> dict:
        return {"offset": self.offset, "line": self.line, "ino": self.ino,
                "emitted": self.emitted, "done": self.done}

    def restore(self, st: dict) -> None:
        self.offset = int(st.get("offset", 0))
        self.line = int(st.get("line", 1))
        self.ino = int(st.get("ino", -1))
        self.emitted = int(st.get("emitted", 0))
        self.done = bool(st.get("done", False))


class FollowScanner:
    """Cursors + suffix scans for one standing query.  ``poll_once``
    returns per-file groups ``(path, records, cursor_state)`` so the
    caller can land each file's records and its advanced cursor in ONE
    atomic journal line.  Match semantics handled here: ``invert``
    (complement over the suffix's lines), ``count_only`` (records carry
    per-wake count deltas, no line text), ``presence_only`` (one record
    per file, scanning stops for that file)."""

    def __init__(self, engine, files, *, invert: bool = False,
                 count_only: bool = False, presence_only: bool = False):
        self.engine = engine
        self.invert = bool(invert)
        self.count_only = bool(count_only)
        self.presence_only = bool(presence_only)
        self.cursors: dict[str, FileCursor] = {
            str(f): FileCursor(path=str(f)) for f in files
        }

    # -- durable state ---------------------------------------------------
    def restore(self, state: dict[str, dict]) -> None:
        for path, st in state.items():
            cur = self.cursors.get(path)
            if cur is not None:
                cur.restore(st)

    def any_selected(self) -> bool:
        return any(c.emitted for c in self.cursors.values())

    # -- scanning --------------------------------------------------------
    def poll_once(self, final: bool = False,
                  limits: dict[str, int] | None = None
                  ) -> list[tuple[str, list[dict], dict]]:
        """One wake over every file: scan grown suffixes, return
        ``[(path, records, cursor_state), ...]`` for files with news.
        ``final=True`` additionally scans an unterminated tail line
        (stream teardown — the idle-exit/finalize path that makes the
        output equal the one-shot oracle even without a trailing
        newline).  ``limits`` (the fused tier's join catch-up) restricts
        the wake to the listed paths and caps each file's suffix read at
        its byte budget: group cursors are line starts, so the capped
        read's last byte is a newline and the member lands EXACTLY on
        the group cursor (or steps toward it in MAX_WAKE_BYTES hops)."""
        groups: list[tuple[str, list[dict], dict]] = []
        scanned = 0
        for cur in self.cursors.values():
            cap = None
            if limits is not None:
                cap = limits.get(cur.path)
                if cap is None or cap <= 0:
                    continue
            snap = cur.state()
            try:
                records = self._poll_file(cur, final, cap)
            except OSError:
                # per-file fault isolation: a file unlinked between the
                # stat and the open (or any transient read error) must
                # not discard the OTHER files' already-scanned groups —
                # restore THIS cursor (a half-applied reset/advance would
                # otherwise skip lines) and move on; next wake retries
                cur.restore(snap)
                log.exception("follow poll failed for %s", cur.path)
                continue
            if records is None:
                continue
            recs, n_bytes = records
            scanned += n_bytes
            if recs or n_bytes:
                groups.append((cur.path, recs, cur.state()))
        if groups:
            _count("follow_wakes")
        if scanned:
            _count("suffix_bytes_scanned", scanned)
        return groups

    def _poll_file(self, cur: FileCursor, final: bool, cap: int | None = None):
        """(records, suffix_bytes) for one file, or None when nothing
        changed.  Truncation/replacement (validator-tuple drift: size
        below the cursor, or a new inode) emits a ``reset`` record and
        rescans from offset 0 — the stream consumer drops its view of
        that file's earlier lines; everything after the reset is again
        byte-identical to a one-shot scan of the new content."""
        try:
            st = os.stat(cur.path)
        except OSError:
            return None  # not created yet / vanished: keep the cursor
        records: list[dict] = []
        if st.st_size < cur.offset or (cur.ino >= 0 and st.st_ino != cur.ino):
            records.append({"file": cur.path, "reset": True})
            cur.offset = 0
            cur.line = 1
            cur.emitted = 0
            cur.done = False
            cur.seen = -1  # a same-size replacement must rescan
        cur.ino = int(st.st_ino)
        if st.st_size <= cur.offset:
            return (records, 0) if records else None
        if self.presence_only and cur.done:
            return (records, 0) if records else None
        if not final and st.st_size == cur.seen:
            # the bytes past the cursor are a known unterminated tail and
            # the file has not grown since the last no-progress scan:
            # skip the re-read (a giant newline-free tail would otherwise
            # be re-read from disk at every poll)
            return (records, 0) if records else None
        res, consumed, data = self.engine.scan_file_suffix(
            cur.path, cur.offset, final=final,
            max_bytes=(MAX_WAKE_BYTES if cap is None
                       else min(MAX_WAKE_BYTES, cap)),
        )
        if consumed == 0:
            # no complete line in the suffix: remember the size so the
            # carry is not re-read until growth (cleared above on reset)
            cur.seen = int(st.st_size)
            return (records, 0) if records else None
        records.extend(self._emit(cur, res, data))
        cur.offset += consumed
        return records, consumed

    def _emit(self, cur: FileCursor, res, data: bytes) -> list[dict]:
        """Records for one scanned suffix; advances ``cur.line`` and
        ``cur.emitted``.  Line numbers are file-global: suffix-local line
        ``k`` is global ``cur.line + k - 1`` (the cursor sits at a line
        start by construction)."""
        import numpy as np

        from distributed_grep_tpu.ops import lines as lines_mod

        nl_idx = lines_mod.newline_index(data)
        n_lines = len(nl_idx) + (0 if data.endswith(b"\n") else 1)
        matched = res.matched_lines
        if self.invert:
            matched = np.setdiff1d(
                np.arange(1, n_lines + 1, dtype=np.int64), matched
            )
        records: list[dict] = []
        selected = int(matched.size)
        if self.presence_only:
            if selected:
                records.append({"file": cur.path, "match": True})
                cur.emitted += selected
                cur.done = True
        elif self.count_only:
            if selected:
                # never materialize lines: the match-dense worst case is
                # a bandwidth-bound counter update
                records.append({"file": cur.path, "count": selected})
                cur.emitted += selected
        else:
            for ln in matched.tolist():
                # line_span's end EXCLUDES the newline — the slice is the
                # line text verbatim
                s, e = lines_mod.line_span(nl_idx, int(ln), len(data))
                text = data[s:e]
                records.append({
                    "file": cur.path,
                    "line": cur.line + int(ln) - 1,
                    # surrogateescape: arbitrary bytes round-trip through
                    # the json journal/stream exactly (the repo-wide
                    # pattern-bytes convention); display layers
                    # re-encode and replace-decode
                    "text": text.decode("utf-8", "surrogateescape"),
                })
            cur.emitted += selected
        cur.line += n_lines
        return records


# ------------------------------------------------------------ durability
class FollowLog:
    """Durable wake log in the job workdir (TaskJournal mechanics).  One
    line per (wake, file): the advanced cursor and the records it
    emitted land ATOMICALLY — replay can neither lose a line whose
    cursor advanced nor duplicate one whose advance never committed."""

    FILENAME = "follow.jsonl"
    # Startup compaction threshold: a log past this size rewrites as a
    # bounded snapshot (cursors + retained tail) at runner construction —
    # the wake stream is unbounded, the durable state it encodes is not.
    COMPACT_BYTES = 1 << 20
    # Records retained by replay (and therefore by compaction): bounds
    # restart memory no matter how long the standing query streamed.
    REPLAY_TAIL_RECORDS = 8192

    def __init__(self, path: str | Path):
        self._journal = TaskJournal(path)

    def record_wake(self, path: str, cursor: dict, seq0: int,
                    records: list[dict]) -> None:
        self._journal.record({
            "kind": "wake", "file": path, "cursor": cursor,
            "seq0": seq0, "records": records, "t": time.time(),
        })

    def close(self) -> None:
        self._journal.close()

    @staticmethod
    def replay(path: str | Path):
        """(cursors, next_seq, tail): per-file latest cursor state, the
        next record sequence number, and the last REPLAY_TAIL_RECORDS
        (seq, record) pairs in order (the caller preloads them into its
        ring — retaining the full history would make restart memory
        proportional to everything the query ever streamed).  Records
        whose seq was already assigned are SKIPPED: a wake whose journal
        line landed but whose fsync failed re-journals the same records
        under the same seq0 after the cursor rollback, and first-
        occurrence-wins keeps the ring's contiguous-seq invariant."""
        cursors: dict[str, dict] = {}
        next_seq = 1
        tail: deque = deque(maxlen=FollowLog.REPLAY_TAIL_RECORDS)
        for e in TaskJournal.replay(path):
            if e.get("kind") != "wake":
                continue
            f = e.get("file")
            if isinstance(f, str) and isinstance(e.get("cursor"), dict):
                cursors[f] = e["cursor"]
            seq = int(e.get("seq0", next_seq))
            for rec in e.get("records") or []:
                if seq >= next_seq:
                    tail.append((seq, rec))
                seq += 1
            next_seq = max(next_seq, seq)
        return cursors, next_seq, list(tail)

    @staticmethod
    def compact(path: str | Path, cursors: dict[str, dict], next_seq: int,
                tail: list[tuple[int, dict]]) -> None:
        """Rewrite the wake log as its bounded snapshot (tmp + fsync +
        rename, the registry-compaction mechanics): the retained tail in
        seq order — replayable records-only lines — then one cursor line
        per file stamped seq0=next_seq so a replay reproduces the exact
        (cursors, next_seq, tail) it was built from."""
        p = Path(path)
        tmp = p.with_name(p.name + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            for seq, rec in tail:
                f.write(json.dumps(
                    {"kind": "wake", "file": str(rec.get("file", "")),
                     "seq0": seq, "records": [rec]},
                    sort_keys=True) + "\n")
            for fp, st in cursors.items():
                f.write(json.dumps(
                    {"kind": "wake", "file": fp, "cursor": st,
                     "seq0": next_seq, "records": []},
                    sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)


# ------------------------------------------------------------ streaming
class StreamRing:
    """Bounded subscriber buffer: publish never blocks (the scan loop is
    the producer), eviction is oldest-first past the byte cap, and a
    reader whose cursor fell behind learns HOW MANY records it lost
    (the explicit ``dropped`` count) before continuing from the oldest
    retained record."""

    # Per-read response bound: a catch-up reader drains in pages instead
    # of one giant JSON body.
    MAX_READ_RECORDS = 1024

    def __init__(self, cap_bytes: int | None = None, start_seq: int = 1):
        self.cap_bytes = (
            env_stream_buffer() if cap_bytes is None else int(cap_bytes)
        )
        self._lock = lockdep.make_lock("follow-stream")
        self._cond = threading.Condition(self._lock)
        self._dq: deque = deque()  # (seq, record, approx_bytes)
        self._bytes = 0
        self.next_seq = int(start_seq)
        self._closed = False

    @staticmethod
    def _size(rec: dict) -> int:
        return 48 + sum(len(str(k)) + len(str(v)) for k, v in rec.items())

    def publish(self, records: list[dict]) -> int:
        """Append records (assigning sequence numbers), shed oldest past
        the cap.  Returns the first assigned seq."""
        if not records:
            return self.next_seq
        dropped = 0
        with self._cond:
            seq0 = self.next_seq
            for rec in records:
                sz = self._size(rec)
                self._dq.append((self.next_seq, rec, sz))
                self._bytes += sz
                self.next_seq += 1
            while self._bytes > self.cap_bytes and len(self._dq) > 1:
                _seq, _rec, sz = self._dq.popleft()
                self._bytes -= sz
                dropped += 1
            self._cond.notify_all()
        if dropped:
            _count("stream_dropped_records", dropped)
        return seq0

    def read_since(self, cursor: int, timeout: float = 0.0):
        """(records, next_cursor, dropped): records with seq > ``cursor``
        (each carries its ``seq``), the cursor to pass next, and how many
        records between ``cursor`` and the oldest retained one were shed
        (0 for a keeping-up consumer).  Waits up to ``timeout`` for news
        when nothing is pending (long-poll)."""
        cursor = max(0, int(cursor))
        deadline = time.monotonic() + max(0.0, timeout)
        with self._cond:
            while not self._closed:
                if self._dq and self._dq[-1][0] > cursor:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, 0.5))
            out: list[dict] = []
            dropped = 0
            nxt = cursor
            if self._dq and self._dq[-1][0] > cursor:
                first_seq = self._dq[0][0]
                if first_seq > cursor + 1:
                    dropped = first_seq - 1 - cursor
                # ring seqs are CONTIGUOUS (publish appends consecutive
                # seqs, shed pops the head, preload seeds a journal tail
                # whose wake lines assigned them consecutively), so the
                # page start is arithmetic — never a scan of the ring
                start = max(0, cursor + 1 - first_seq)
                for seq, rec, _sz in itertools.islice(
                    self._dq, start, start + self.MAX_READ_RECORDS
                ):
                    out.append({"seq": seq, **rec})
                    nxt = seq
        return out, nxt, dropped

    def preload(self, tail: list[tuple[int, dict]]) -> None:
        """Seed the ring from a replayed journal tail (restart path): the
        records keep their original sequence numbers; anything beyond
        the byte cap sheds oldest-first exactly like a live publish —
        but WITHOUT counting into stream_dropped_records (nothing was
        dropped; the full history stays in the journal)."""
        with self._cond:
            for seq, rec in tail:
                if seq >= self.next_seq:
                    continue  # replay seeded next_seq past the tail
                sz = self._size(rec)
                self._dq.append((seq, rec, sz))
                self._bytes += sz
            while self._bytes > self.cap_bytes and len(self._dq) > 1:
                _seq, _rec, sz = self._dq.popleft()
                self._bytes -= sz

    def close(self) -> None:
        """Wake every long-polling reader (daemon stop / job cancel)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()


# --------------------------------------------------------------- runner
class FollowRunner:
    """One daemon-side standing query: engine + scanner + wake loop +
    durable log + subscriber ring.  Constructed OUTSIDE the service lock
    (journal open + log replay are filesystem work — the _flush_starts
    contract); the engine builds lazily on the runner thread (model
    compile can take seconds; on a chip, the first XLA compile 20-40 s).

    Ordering per wake and file: journal line FIRST (fsync), ring publish
    second — durability before visibility, so a crash between the two
    re-serves the already-durable records from the replayed tail instead
    of losing them."""

    def __init__(self, job_id: str, config, work_root: str | Path, *,
                 event_log=None, on_fail=None, write_gate=None,
                 groups=None):
        self.job_id = job_id
        self.config = config
        self.event_log = event_log
        self.on_fail = on_fail
        # Fused tier (round 21): the daemon's FollowGroupRegistry, or
        # None (DGREP_FOLLOW_FUSE=0 / one-shot CLI) — then start() is
        # the pre-round-21 solo thread, byte for byte.
        self.groups = groups
        self.fused = False  # True while a FollowGroup drives this runner
        # Daemon-scope write fence (round 18 HA failover): consulted
        # before each wake's journal writes.  A False answer means this
        # daemon lost the work-root lease — the wake is ABANDONED before
        # any cursor advances or record publishes (the promoted daemon
        # resumed the standing query from follow.jsonl; a stale append
        # would corrupt ITS cursor replay) and the loop stops.  None
        # (single-daemon) skips the check entirely.
        self.write_gate = write_gate
        self.poll_s = env_follow_poll_s(
            float(config.follow_poll_s or DEFAULT_FOLLOW_POLL_S)
        )
        self._log_path = Path(work_root) / FollowLog.FILENAME
        cursors, next_seq, tail = FollowLog.replay(self._log_path)
        self._resume_cursors = cursors
        self.resumed = bool(cursors)
        self.ring = StreamRing(start_seq=next_seq)
        # preload the durable tail so a subscriber reconnecting across a
        # restart continues from its cursor without a gap (older records
        # beyond the cap shed exactly like a slow consumer's)
        self.ring.preload(tail)
        try:
            if (self._log_path.exists()
                    and self._log_path.stat().st_size
                    > FollowLog.COMPACT_BYTES):
                # the wake stream is unbounded; its durable state is not —
                # rewrite the log as the snapshot replay just produced
                # (disk stays bounded, the NEXT restart replays in O(tail))
                FollowLog.compact(self._log_path, cursors, next_seq, tail)
        except OSError:
            log.exception("follow log compaction failed for %s", job_id)
        self._log = FollowLog(self._log_path)
        self._log_dirty = False
        self._scanner: FollowScanner | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.wakes = 0
        self.error = ""
        self.started_at = time.time()

    # -- engine construction (lazy: ops stack imports live here only) ----
    def _build_engine(self):
        from distributed_grep_tpu.ops.engine import cached_engine

        opts = dict(self.config.effective_app_options())
        patterns = opts.get("patterns")
        pattern = opts.get("pattern") if patterns is None else None
        if isinstance(pattern, bytes):
            pattern = pattern.decode("utf-8", "surrogateescape")
        engine, _verdict = cached_engine(
            pattern,
            patterns=list(patterns) if patterns is not None else None,
            ignore_case=bool(opts.get("ignore_case", False)),
            # host scanning by default: the daemon's standing queries are
            # latency-bound small suffixes; "device" opts in explicitly
            backend=("device" if opts.get("backend") == "device" else "cpu"),
        )
        return engine

    def _make_scanner(self, engine) -> FollowScanner:
        """Cursors + emit semantics around ``engine`` — which may be
        None (a fused group member: the group's union scan feeds
        ``_emit`` directly; the engine attaches lazily only for join
        catch-up or after a demotion to solo)."""
        opts = dict(self.config.effective_app_options())
        scanner = FollowScanner(
            engine, list(self.config.input_files),
            invert=bool(opts.get("invert", False)),
            count_only=bool(opts.get("count_only", False)),
            presence_only=bool(opts.get("presence_only", False)),
        )
        scanner.restore(self._resume_cursors)
        return scanner

    def _build_scanner(self) -> FollowScanner:
        return self._make_scanner(self._build_engine())

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        if self.groups is not None and self.groups.adopt(self):
            return  # a FollowGroup's shared wake thread drives this runner
        self.start_solo()

    def start_solo(self) -> None:
        """Spawn the solo wake thread — the only path when the registry
        is absent (DGREP_FOLLOW_FUSE=0 / CLI) or the config is
        group-ineligible, and the fall-back landing for a demoted group
        member (whose scanner keeps the exact cursors; only the engine
        is missing and attaches on the first solo wake)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self.fused = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"follow-{self.job_id}"
        )
        self._thread.start()

    def request_stop(self) -> None:
        """Pure state (safe under any lock): the loop exits at its next
        wake check; readers wake via ring.close()."""
        self._stop.set()

    def close(self, join_timeout_s: float = 10.0) -> None:
        """Teardown outside every service lock: stop the loop, wake the
        subscribers, close the log.  Safe from the runner thread itself
        (the engine-build-failure path: on_fail → service close flush
        runs ON this thread — joining it would raise and skip the log
        close below)."""
        self._stop.set()
        if self.groups is not None:
            # blocks on the group's wake lock: an in-flight group wake
            # finishes its writes to this member's log/ring first; after
            # this the group never touches the runner again (no-op for
            # solo runners)
            self.groups.discard(self)
        self.ring.close()
        if (self._thread is not None
                and self._thread is not threading.current_thread()):
            self._thread.join(timeout=join_timeout_s)
        try:
            self._log.close()
        except Exception:  # noqa: BLE001 — teardown must not raise
            log.exception("follow log close failed for %s", self.job_id)

    def _run(self) -> None:
        if self._stop.is_set():
            return  # cancelled between publish and start: skip the build
        try:
            if self._scanner is None:
                self._scanner = self._build_scanner()
            elif self._scanner.engine is None:
                # demoted from a fused group: the member scanner carries
                # the exact cursors; only the engine is missing
                self._scanner.engine = self._build_engine()
        except Exception as e:  # noqa: BLE001 — bad query, healthy daemon
            log.exception("follow job %s failed to build its engine",
                          self.job_id)
            self.error = str(e)
            self.ring.close()
            if self.on_fail is not None:
                self.on_fail(self.job_id, str(e))
            return
        while not self._stop.is_set():
            try:
                self.wake_once()
            except Exception:  # noqa: BLE001 — one bad wake must not kill
                # the standing query (the file may reappear/recover)
                log.exception("follow wake failed for %s", self.job_id)
            self._stop.wait(self.poll_s)

    def wake_once(self) -> int:
        """One wake: scan, journal, publish.  Returns records emitted
        (tests and the benchmark drive this directly)."""
        if self.write_gate is not None and not self.write_gate():
            # deposed: no scan, no journal line, no publish — and no
            # further wakes (request_stop is pure state, safe here)
            self.request_stop()
            return 0
        if self._scanner is None:
            self._scanner = self._build_scanner()
        elif self._scanner.engine is None:
            self._scanner.engine = self._build_engine()
        if self._log_dirty:
            # a failed journal write may have torn a line mid-file; a
            # plain append would glue the next record onto the fragment
            # and make replay discard everything after it — reopen first
            # (the TaskJournal constructor truncates the torn tail)
            try:
                self._log.close()
            except Exception:  # noqa: BLE001 — the handle may be dead
                log.exception("follow log close-for-reopen failed")
            self._log = FollowLog(self._log_path)
            self._log_dirty = False
        # pre-wake cursor snapshot: a journal write failing mid-loop
        # (disk-full blip) must roll the NOT-yet-journaled groups'
        # in-memory cursors back, or the next wake would scan past lines
        # nobody ever saw — the live no-lost-line half of the contract
        # (the journaled groups keep their advance; restart replays the
        # same state either way)
        snap = {p: c.state() for p, c in self._scanner.cursors.items()}
        groups = self._scanner.poll_once()
        emitted = 0
        for i, (path, records, cursor) in enumerate(groups):
            seq0 = self.ring.next_seq
            # durability before visibility (and the cursor advance rides
            # the SAME fsync'd line as its records — the no-dup/no-loss
            # restart argument)
            try:
                self._log.record_wake(path, cursor, seq0, records)
            except Exception:
                self._log_dirty = True  # reopen before the next append
                for p2, _recs2, _cur2 in groups[i:]:
                    c2 = self._scanner.cursors.get(p2)
                    if c2 is not None and p2 in snap:
                        c2.restore(snap[p2])
                raise
            self.ring.publish(records)
            emitted += len(records)
        if groups:
            self.wakes += 1
            if self.event_log is not None:
                try:
                    self.event_log.write({
                        "t": "instant", "name": "follow:wake",
                        "cat": "follow", "ts": time.time(),
                        "job": self.job_id,
                        "args": {"files": len(groups), "records": emitted},
                    })
                except Exception:  # noqa: BLE001 — telemetry only
                    log.exception("follow:wake event write failed")
        return emitted

    # -- fused-tier entries (called from a FollowGroup's wake thread) ----
    def fused_commit(self, path: str, cursor: dict,
                     records: list[dict]) -> None:
        """Journal + publish one (file, wake) for this member — the same
        journal-first ordering and torn-line reopen discipline as
        wake_once, minus the scan (the group already ran the shared
        union scan).  Raises on journal failure: the caller rolls this
        member's cursor back and demotes it to solo."""
        if self._log_dirty:
            try:
                self._log.close()
            except Exception:  # noqa: BLE001 — the handle may be dead
                log.exception("follow log close-for-reopen failed")
            self._log = FollowLog(self._log_path)
            self._log_dirty = False
        seq0 = self.ring.next_seq
        try:
            self._log.record_wake(path, cursor, seq0, records)
        except Exception:
            self._log_dirty = True  # reopen before the next append
            raise
        self.ring.publish(records)

    def note_fused_wake(self, n_files: int, n_records: int, *,
                        fused: bool = True) -> None:
        """Wake accounting + the explain instant for a group-driven
        wake: fused wakes write ``fuse:wake`` (dgrep explain's
        fused-route signal); join catch-up wakes — solo semantics on the
        group thread — keep the solo ``follow:wake`` name."""
        self.wakes += 1
        if self.event_log is None:
            return
        try:
            self.event_log.write({
                "t": "instant",
                "name": "fuse:wake" if fused else "follow:wake",
                "cat": "follow", "ts": time.time(), "job": self.job_id,
                "args": {"files": n_files, "records": n_records},
            })
        except Exception:  # noqa: BLE001 — telemetry only
            log.exception("follow wake event write failed")

    def status(self) -> dict:
        out: dict = {
            "poll_s": self.poll_s,
            "wakes": self.wakes,
            "files": len(self.config.input_files),
            "next_seq": self.ring.next_seq,
        }
        if self.resumed:
            out["resumed"] = True
        if self.fused:
            out["fused"] = True
        if self.error:
            out["error"] = self.error
        sc = self._scanner
        if sc is not None:
            out["selected"] = int(
                sum(c.emitted for c in sc.cursors.values())
            )
        return out


# ------------------------------------------------------------ fused tier
@dataclass
class _GroupMember:
    """One standing query inside a FollowGroup: the runner it fans into,
    its query spec (the FusedScanner union slot), the group-realpath ->
    member-spelling map (records carry each job's OWN path spellings),
    and its engine-LESS FollowScanner — exact cursors + emit semantics;
    the engine attaches only for join catch-up or after demotion."""

    runner: FollowRunner
    spec: tuple
    paths: dict[str, str]
    scanner: FollowScanner
    catching_up: bool = True


class FollowGroup:
    """ONE wake loop + ONE shared per-file cursor serving K fused
    standing queries: each wake runs one stat + one suffix read + one
    union scan per grown file (ops/fuse.FusedScanner.scan_suffix) and
    fans each member's exact confirmed result into that member's OWN
    FollowLog + StreamRing via FollowRunner.fused_commit — per-job
    durability, torn-tail replay, and reconnect semantics untouched.

    Thread-safety: membership mutates under the registry's pure-state
    lock; all scan/journal work runs under the group's io_ok wake lock
    ("follow-group-wake"), which FollowGroupRegistry.discard also takes
    so a leaving runner is never written to mid-wake.  Lock order: wake
    lock OUTER, registry lock inner (demotions fire under a wake)."""

    def __init__(self, key: tuple, reg: "FollowGroupRegistry"):
        self.key = key
        self._reg = reg
        # shared per-file scan state, keyed by realpath (the key's
        # watched half); offsets/lines are identical across fused
        # members by construction (same content, same cursor)
        self.cursors: dict[str, FileCursor] = {}
        self._members: list[_GroupMember] = []
        self._wake_lock = lockdep.make_lock("follow-group-wake", io_ok=True)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._fused = None  # ops.fuse.FusedScanner for the current members
        self._fused_specs: tuple = ()
        self.poll_s = DEFAULT_FOLLOW_POLL_S
        self.wakes = 0
        self.last_wake = time.monotonic()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"follow-group-{id(self):x}",
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.wake_once()
            except Exception:  # noqa: BLE001 — one bad wake must not kill
                # the group (files may reappear/recover next wake)
                log.exception("fused follow wake failed")
            self._stop.wait(self.poll_s)

    def members(self) -> list[_GroupMember]:
        with self._reg._lock:
            return list(self._members)

    def _recompute_cadence_locked(self) -> None:
        # cadence = the tightest member's poll_s (pure state — callable
        # under the registry lock)
        if self._members:
            self.poll_s = min(m.runner.poll_s for m in self._members)

    # -- the group wake --------------------------------------------------
    def wake_once(self) -> int:
        """One group wake (tests and the benchmark drive this directly):
        catch the joiners up, then ONE shared suffix scan per grown file
        fanned into every fused member.  Returns records emitted."""
        with self._wake_lock:
            return self._wake_under_lock()

    def _wake_under_lock(self) -> int:
        gate = self._reg.write_gate
        if gate is not None and not gate():
            # deposed daemon (round 18 fence): stop every member BEFORE
            # any journal write — the promoted daemon owns the cursors
            for m in self.members():
                m.runner.request_stop()
            self._stop.set()
            return 0
        self.last_wake = time.monotonic()
        emitted = 0
        for m in self.members():
            if m.catching_up and not m.runner._stop.is_set():
                emitted += self._catch_up(m)
        fused = [m for m in self.members()
                 if not m.catching_up and not m.runner._stop.is_set()]
        if not fused:
            return emitted
        if not self._ensure_union(fused):
            return emitted  # FuseError: every member just went solo
        tally: dict[str, list[int]] = {
            m.runner.job_id: [0, 0] for m in fused
        }
        dead: set[int] = set()
        news = False
        for real in sorted(self.cursors):
            n = self._wake_file(self.cursors[real], fused, dead, tally)
            if n is None:
                return emitted  # truncation: the whole group went solo
            if n:
                news = True
                emitted += n
        if news:
            self.wakes += 1
            # base counter parity with the solo path: the GROUP's one
            # scan pass counts as one wake (K-flatness is the point)
            _count("follow_wakes")
            alive = [m for m in fused if id(m) not in dead]
            if len(alive) >= 2:
                _count_fused("follow_fused_wakes")
            for m in alive:
                files, recs = tally[m.runner.job_id]
                if files:
                    m.runner.note_fused_wake(files, recs)
        return emitted

    def _wake_file(self, gcur: FileCursor, fused: list[_GroupMember],
                   dead: set[int], tally: dict[str, list[int]]):
        """One shared suffix scan fanned into every fused member.
        Returns records emitted, 0 when the file had no news, or None
        when truncation/replacement demoted the group to solo."""
        try:
            st = os.stat(gcur.path)
        except OSError:
            return 0  # not created yet / vanished: keep the cursor
        if st.st_size < gcur.offset or (
                gcur.ino >= 0 and st.st_ino != gcur.ino):
            # truncation/replacement: fall the WHOLE group back to solo —
            # each member's own runner re-detects the reset against its
            # durable cursor and emits its exact reset record + rescan
            # (the reset path stays the single solo-tested one; fusion
            # is never a correctness dependency)
            self._demote_all()
            return None
        gcur.ino = int(st.st_ino)
        if st.st_size <= gcur.offset:
            return 0
        if st.st_size == gcur.seen:
            return 0
        try:
            results, consumed, data = self._fused.scan_suffix(
                gcur.path, gcur.offset, max_bytes=MAX_WAKE_BYTES
            )
        except OSError:
            log.exception("fused follow scan failed for %s", gcur.path)
            return 0  # transient read error: next wake retries
        if consumed == 0:
            gcur.seen = int(st.st_size)
            return 0
        # ONE read + one union scan for K members: the base counter
        # ticks once per shared scan (the flat-in-K figure the benchmark
        # pins); the saved counter prices what solo runners would have
        # re-read and re-scanned
        _count("suffix_bytes_scanned", consumed)
        live = [m for m in fused if id(m) not in dead
                and not m.runner._stop.is_set()]
        if len(live) >= 2:
            _count_fused("follow_suffix_bytes_saved",
                         consumed * (len(live) - 1))
        n_records = 0
        for k, m in enumerate(fused):
            if id(m) in dead or m.runner._stop.is_set():
                continue
            mpath = m.paths[gcur.path]
            mcur = m.scanner.cursors[mpath]
            snap = mcur.state()
            recs = m.scanner._emit(mcur, results[k], data)
            mcur.offset += consumed
            mcur.ino = gcur.ino
            try:
                m.runner.fused_commit(mpath, mcur.state(), recs)
            except Exception:  # noqa: BLE001 — journal fault: this
                # member falls back to solo with its cursor rolled back
                # (no line lost, none duplicated); the others continue
                log.exception("fused commit failed for %s — demoting",
                              m.runner.job_id)
                mcur.restore(snap)
                dead.add(id(m))
                self._demote(m)
                continue
            t = tally[m.runner.job_id]
            t[0] += 1
            t[1] += len(recs)
            n_records += len(recs)
        gcur.offset += consumed
        # consumed > 0 under final=False means data ends at a newline,
        # so the line advance is exactly the newline count
        gcur.line += data.count(b"\n")
        return n_records

    def _ensure_union(self, fused: list[_GroupMember]) -> bool:
        """(Re)build the FusedScanner when membership changed.  Specs
        ride the cross-job model cache, so a stable group pays zero
        compiles per rebuild.  FuseError/any failure demotes every
        member to solo and answers False."""
        specs = tuple(m.spec for m in fused)
        if self._fused is not None and specs == self._fused_specs:
            return True
        try:
            from distributed_grep_tpu.ops.fuse import FusedScanner

            opts = dict(fused[0].runner.config.effective_app_options())
            self._fused = FusedScanner(
                list(specs),
                backend=("device" if opts.get("backend") == "device"
                         else "cpu"),
            )
            self._fused_specs = specs
            return True
        except Exception:  # noqa: BLE001 — union outside every subset
            log.exception("fused follow union build failed — solo fallback")
            self._fused = None
            self._fused_specs = ()
            self._demote_all()
            return False

    def _catch_up(self, m: _GroupMember) -> int:
        """Advance a joiner from its durable cursor to the group cursor
        (solo semantics on the group thread, byte-budgeted so the capped
        suffix read cuts exactly at the group cursor — both are line
        starts).  A member AHEAD of the group (a demoted-then-readopted
        resume) or anchored to a different inode goes solo: only
        behind-or-aligned members can fuse without re-emitting."""
        limits: dict[str, int] = {}
        for real, gcur in self.cursors.items():
            mpath = m.paths.get(real)
            mcur = m.scanner.cursors.get(mpath) if mpath else None
            if mcur is None:
                self._demote(m)
                return 0
            if mcur.offset > gcur.offset or (
                    mcur.ino >= 0 and gcur.ino >= 0
                    and mcur.ino != gcur.ino):
                self._demote(m)
                return 0
            if mcur.offset < gcur.offset:
                limits[mpath] = gcur.offset - mcur.offset
        if not limits:
            m.catching_up = False
            m.runner.fused = True
            return 0
        if m.scanner.engine is None:
            try:
                m.scanner.engine = m.runner._build_engine()
            except Exception:  # noqa: BLE001 — bad query/env: the solo
                # runner's engine-failure path owns the job-fail report
                log.exception("fused catch-up engine build failed for %s",
                              m.runner.job_id)
                self._demote(m)
                return 0
        snap = {p: c.state() for p, c in m.scanner.cursors.items()}
        try:
            groups = m.scanner.poll_once(limits=limits)
        except Exception:  # noqa: BLE001
            log.exception("fused catch-up scan failed for %s",
                          m.runner.job_id)
            for p, st in snap.items():
                c = m.scanner.cursors.get(p)
                if c is not None:
                    c.restore(st)
            self._demote(m)
            return 0
        emitted = 0
        for i, (path, records, cursor) in enumerate(groups):
            try:
                m.runner.fused_commit(path, cursor, records)
            except Exception:  # noqa: BLE001 — journal fault: roll back
                # the uncommitted groups and let the solo runner retry
                log.exception("fused catch-up commit failed for %s",
                              m.runner.job_id)
                for p2, _r2, _c2 in groups[i:]:
                    c2 = m.scanner.cursors.get(p2)
                    if c2 is not None and p2 in snap:
                        c2.restore(snap[p2])
                self._demote(m)
                return emitted
            emitted += len(records)
        if groups:
            m.runner.note_fused_wake(len(groups), emitted, fused=False)
        return emitted

    def _demote(self, m: _GroupMember) -> None:
        self._reg.demote(self, m)

    def _demote_all(self) -> None:
        for m in self.members():
            self._reg.demote(self, m)

    # -- telemetry -------------------------------------------------------
    def status(self) -> dict:
        with self._reg._lock:
            members = list(self._members)
        row: dict = {
            "members": len(members),
            "jobs": [m.runner.job_id for m in members],
            "files": len(self.cursors),
            "poll_s": self.poll_s,
            "wakes": self.wakes,
            "cursor_bytes": int(
                sum(c.offset for c in self.cursors.values())
            ),
            # now-minus-last-wake: a stalled group runner shows here
            # before subscribers notice shed records (dgrep top renders
            # this per group)
            "wake_lag_s": round(
                max(0.0, time.monotonic() - self.last_wake), 3
            ),
        }
        catching = sum(1 for m in members if m.catching_up)
        if catching:
            row["catching_up"] = catching
        return row


class FollowGroupRegistry:
    """Daemon-scope group table for the fused follow tier.  ``adopt``
    routes a starting FollowRunner into its group (creating one per
    runtime/fusion.follow_fusion_key); ``discard`` removes a stopping
    runner; ``demote`` falls a member back to its solo runner.  The
    registry lock ("follow-groups") is PURE STATE — key computation
    (realpath stats) and every scan/journal run outside it
    (analyze: locked-blocking); group wake locks are io_ok and OUTER to
    it (lock-order)."""

    def __init__(self, *, write_gate=None, start_threads: bool = True,
                 auto_solo: bool = True):
        from distributed_grep_tpu.runtime.fusion import env_fuse_max_queries

        self._lock = lockdep.make_lock("follow-groups")
        self._groups: dict[tuple, FollowGroup] = {}
        self.write_gate = write_gate
        # test hooks: start_threads=False drives group.wake_once
        # manually; auto_solo=False leaves demoted runners unstarted so
        # a test can inspect the handoff state deterministically
        self.start_threads = start_threads
        self.auto_solo = auto_solo
        self.max_members = env_fuse_max_queries()

    def adopt(self, runner: FollowRunner) -> bool:
        """Route a starting runner into a fused group when its config is
        group-eligible.  False → the caller runs solo (the pre-round-21
        path, byte for byte).  Key computation (realpath) runs BEFORE
        the membership lock; the lock itself is dict/list surgery."""
        from distributed_grep_tpu.runtime.fusion import (
            follow_fusion_key,
            query_spec,
        )

        key = follow_fusion_key(runner.config)
        if key is None:
            return False
        spec = query_spec(dict(runner.config.effective_app_options()))
        if spec is None:
            return False  # fusion_key implies a spec; stay defensive
        paths: dict[str, str] = {}
        for f in runner.config.input_files:
            paths[os.path.realpath(os.fspath(f))] = str(f)
        if len(paths) != len(runner.config.input_files):
            # two spellings of one file: the solo scanner keeps a cursor
            # per spelling (scans it twice per wake) — there is no
            # shared-cursor form of that; solo serves it unchanged
            return False
        member = _GroupMember(
            runner=runner, spec=spec, paths=paths,
            scanner=runner._make_scanner(None),
        )
        fresh: FollowGroup | None = None
        with self._lock:
            group = self._groups.get(key)
            if group is None or group._stop.is_set():
                group = FollowGroup(key, self)
                for real, mpath in member.paths.items():
                    gcur = FileCursor(path=real)
                    gcur.restore(member.scanner.cursors[mpath].state())
                    group.cursors[real] = gcur
                self._groups[key] = group
                fresh = group
            elif len(group._members) >= self.max_members:
                # DGREP_FUSE_MAX_QUERIES bounds the union automaton and
                # one lost wake's blast radius, exactly like batch fusion
                return False
            group._members.append(member)
            group._recompute_cadence_locked()
            runner._scanner = member.scanner
        _count_fused("follow_fused_queries")
        if fresh is not None and self.start_threads:
            fresh.start()
        return True

    def demote(self, group: FollowGroup, member: _GroupMember) -> None:
        """Remove a member and fall it back to its solo runner (called
        from the group's wake thread, under the wake lock).  The LAST
        demotion retires the group."""
        empty = False
        with self._lock:
            if member in group._members:
                group._members.remove(member)
            group._recompute_cadence_locked()
            if not group._members:
                self._groups.pop(group.key, None)
                empty = True
        member.runner.fused = False
        if empty:
            group._stop.set()
        if self.auto_solo and not member.runner._stop.is_set():
            member.runner.start_solo()

    def discard(self, runner: FollowRunner) -> None:
        """Detach a stopping runner (job cancel / daemon stop).  Takes
        the group's wake lock FIRST (lock order: wake OUTER, registry
        inner) so an in-flight group wake finishes its writes to this
        runner's log/ring before close() tears them down."""
        found = None
        with self._lock:
            for g in self._groups.values():
                for m in g._members:
                    if m.runner is runner:
                        found = (g, m)
                        break
                if found:
                    break
        if found is None:
            return
        g, m = found
        with g._wake_lock:
            empty = False
            with self._lock:
                if m in g._members:
                    g._members.remove(m)
                g._recompute_cadence_locked()
                if not g._members:
                    self._groups.pop(g.key, None)
                    empty = True
            if empty:
                g._stop.set()
        runner.fused = False

    def status_rows(self) -> list[dict]:
        """Per-group /status rows (computed outside the service lock;
        the registry lock only snapshots the group list)."""
        with self._lock:
            groups = list(self._groups.values())
        return [g.status() for g in groups]

    def close(self) -> None:
        """Stop every group loop (daemon-stop safety net — normally the
        last member's discard already retired each group)."""
        with self._lock:
            groups = list(self._groups.values())
            self._groups.clear()
        for g in groups:
            g._stop.set()

"""Streaming tier (round 17): standing queries over live-append inputs.

The batch runtime answers "what matched" for a corpus frozen at submit
time; the workload a production grep service actually carries is the log
tail — files that GROW while the query is standing.  This module makes
live-append a first-class regime:

* ``FollowScanner`` — per-file durable cursors (byte offset of the first
  INCOMPLETE line, always a line start) + suffix scans through
  ``GrepEngine.scan_file_suffix``: each wake scans ONLY the appended
  complete-line suffix; the partial tail line is carried and re-scanned
  extended on the next wake, so emitted lines are byte-identical to a
  one-shot scan over the final file state (the oracle every test pins).
  Exactness at every append boundary rides the repo's load-bearing
  invariant — the DFA '\\n'-column==start reset means a buffer that
  begins at a line start and ends at a line boundary scans exactly like
  the same lines inside a whole-file scan, on every kernel family.
  Truncation/replacement is detected via the validator-tuple rule (size
  below the cursor, or a changed inode — the cp -p + mv case) and
  answers with a ``reset`` record + a full rescan from offset 0.
* ``FollowLog`` — the durable half (TaskJournal mechanics: fsync per
  line, torn tail truncated on reopen): ONE json line per (wake, file)
  carrying the new cursor AND the records it emitted, atomically — a
  daemon restart resumes every standing query from its cursors with no
  duplicate and no lost line (a torn wake line never advanced the
  cursor, so its records simply re-emit; a complete line advanced it
  exactly once).
* ``StreamRing`` — the bounded per-job subscriber buffer behind
  ``GET /jobs/<id>/stream``: the scan loop publishes and NEVER blocks;
  past ``DGREP_STREAM_BUFFER`` bytes the oldest records shed (counted in
  ``stream_dropped_records``) and a consumer whose cursor fell behind
  receives an explicit ``dropped`` count, then continues from the
  oldest retained record.
* ``FollowRunner`` — one daemon-side standing query: engine build
  (ops.engine.cached_engine — imported lazily; this module stays
  importable without the ops stack, like runtime/fusion), wake loop at
  the ``DGREP_FOLLOW_POLL_S`` cadence, journal-before-publish ordering
  (durability before visibility, the registry's submit contract).

Count-only standing queries (``count_only``/``presence_only`` app
options — the CLI's -c/-l/-q) never materialize lines: wake records
carry per-file count deltas, so the match-dense worst case is a
bandwidth-bound counter update.

The follow path never consults the shard index: a stale trigram summary
can therefore never prune a standing query (and the batch entries'
lookups revalidate fresh stats anyway — an append IS stat drift).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from pathlib import Path

from distributed_grep_tpu.runtime.journal import TaskJournal
from distributed_grep_tpu.utils import lockdep
from distributed_grep_tpu.utils.logging import get_logger

log = get_logger("follow")

DEFAULT_FOLLOW_POLL_S = 0.5
DEFAULT_STREAM_BUFFER = 4 << 20

# Per-wake suffix read cap: one wake scans at most this much appended
# data (bounded memory — the catch-up over a huge existing file proceeds
# cap-sized wake by wake; the cursor simply advances in steps).
MAX_WAKE_BYTES = 64 << 20


def env_follow_poll_s(default: float = DEFAULT_FOLLOW_POLL_S) -> float:
    """Standing-query wake cadence — the ONE parser of
    DGREP_FOLLOW_POLL_S (operator override; malformed or <= 0 keeps the
    default, the env_batch_bytes shrug-off policy)."""
    raw = os.environ.get("DGREP_FOLLOW_POLL_S")
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def env_stream_buffer(default: int = DEFAULT_STREAM_BUFFER) -> int:
    """Per-subscriber stream buffer byte cap — the ONE parser of
    DGREP_STREAM_BUFFER (a slow consumer sheds oldest-first past it;
    malformed or < 1 keeps the default)."""
    raw = os.environ.get("DGREP_STREAM_BUFFER")
    if raw is None or raw == "":
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v > 0 else default


# ------------------------------------------------------ module telemetry
# Process-global follow counters, the fusion_counters contract: leaf
# lock, nonzero-only reads, merged into engine.stats (ops/engine.scan
# tail), the worker heartbeat piggyback (worker._engine_cache_counters),
# and the service /status "follow" view — all sys.modules-gated so
# follow-free processes never import this module just to report nothing.
_stats_lock = lockdep.make_lock("follow-stats")
_stats = {
    "follow_wakes": 0,
    "suffix_bytes_scanned": 0,
    "stream_dropped_records": 0,
}


def _count(name: str, n: int = 1) -> None:
    with _stats_lock:
        _stats[name] += n


def follow_counters() -> dict:
    """Copy of the follow counters, or {} when never touched (the
    nonzero-only piggyback/stats contract)."""
    with _stats_lock:
        if not any(_stats.values()):
            return {}
        return dict(_stats)


def follow_counters_clear() -> None:
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0


# ------------------------------------------------------------- cursors
@dataclass
class FileCursor:
    """Durable per-file scan position: ``offset`` is the byte offset of
    the first INCOMPLETE line (always a line start — the suffix-scan
    exactness invariant), ``line`` the 1-based line number at that
    offset.  ``ino`` anchors the validator-tuple truncation rule."""

    path: str
    offset: int = 0
    line: int = 1
    ino: int = -1
    emitted: int = 0  # selected lines so far (exit codes, -c display)
    done: bool = False  # presence settled (presence_only queries)
    # TRANSIENT (not journaled — a restart just rescans once): the stat
    # size of the last no-progress scan, so an unterminated tail is not
    # re-read from disk every wake until the file actually grows
    seen: int = -1

    def state(self) -> dict:
        return {"offset": self.offset, "line": self.line, "ino": self.ino,
                "emitted": self.emitted, "done": self.done}

    def restore(self, st: dict) -> None:
        self.offset = int(st.get("offset", 0))
        self.line = int(st.get("line", 1))
        self.ino = int(st.get("ino", -1))
        self.emitted = int(st.get("emitted", 0))
        self.done = bool(st.get("done", False))


class FollowScanner:
    """Cursors + suffix scans for one standing query.  ``poll_once``
    returns per-file groups ``(path, records, cursor_state)`` so the
    caller can land each file's records and its advanced cursor in ONE
    atomic journal line.  Match semantics handled here: ``invert``
    (complement over the suffix's lines), ``count_only`` (records carry
    per-wake count deltas, no line text), ``presence_only`` (one record
    per file, scanning stops for that file)."""

    def __init__(self, engine, files, *, invert: bool = False,
                 count_only: bool = False, presence_only: bool = False):
        self.engine = engine
        self.invert = bool(invert)
        self.count_only = bool(count_only)
        self.presence_only = bool(presence_only)
        self.cursors: dict[str, FileCursor] = {
            str(f): FileCursor(path=str(f)) for f in files
        }

    # -- durable state ---------------------------------------------------
    def restore(self, state: dict[str, dict]) -> None:
        for path, st in state.items():
            cur = self.cursors.get(path)
            if cur is not None:
                cur.restore(st)

    def any_selected(self) -> bool:
        return any(c.emitted for c in self.cursors.values())

    # -- scanning --------------------------------------------------------
    def poll_once(self, final: bool = False) -> list[tuple[str, list[dict], dict]]:
        """One wake over every file: scan grown suffixes, return
        ``[(path, records, cursor_state), ...]`` for files with news.
        ``final=True`` additionally scans an unterminated tail line
        (stream teardown — the idle-exit/finalize path that makes the
        output equal the one-shot oracle even without a trailing
        newline)."""
        groups: list[tuple[str, list[dict], dict]] = []
        scanned = 0
        for cur in self.cursors.values():
            snap = cur.state()
            try:
                records = self._poll_file(cur, final)
            except OSError:
                # per-file fault isolation: a file unlinked between the
                # stat and the open (or any transient read error) must
                # not discard the OTHER files' already-scanned groups —
                # restore THIS cursor (a half-applied reset/advance would
                # otherwise skip lines) and move on; next wake retries
                cur.restore(snap)
                log.exception("follow poll failed for %s", cur.path)
                continue
            if records is None:
                continue
            recs, n_bytes = records
            scanned += n_bytes
            if recs or n_bytes:
                groups.append((cur.path, recs, cur.state()))
        if groups:
            _count("follow_wakes")
        if scanned:
            _count("suffix_bytes_scanned", scanned)
        return groups

    def _poll_file(self, cur: FileCursor, final: bool):
        """(records, suffix_bytes) for one file, or None when nothing
        changed.  Truncation/replacement (validator-tuple drift: size
        below the cursor, or a new inode) emits a ``reset`` record and
        rescans from offset 0 — the stream consumer drops its view of
        that file's earlier lines; everything after the reset is again
        byte-identical to a one-shot scan of the new content."""
        try:
            st = os.stat(cur.path)
        except OSError:
            return None  # not created yet / vanished: keep the cursor
        records: list[dict] = []
        if st.st_size < cur.offset or (cur.ino >= 0 and st.st_ino != cur.ino):
            records.append({"file": cur.path, "reset": True})
            cur.offset = 0
            cur.line = 1
            cur.emitted = 0
            cur.done = False
            cur.seen = -1  # a same-size replacement must rescan
        cur.ino = int(st.st_ino)
        if st.st_size <= cur.offset:
            return (records, 0) if records else None
        if self.presence_only and cur.done:
            return (records, 0) if records else None
        if not final and st.st_size == cur.seen:
            # the bytes past the cursor are a known unterminated tail and
            # the file has not grown since the last no-progress scan:
            # skip the re-read (a giant newline-free tail would otherwise
            # be re-read from disk at every poll)
            return (records, 0) if records else None
        res, consumed, data = self.engine.scan_file_suffix(
            cur.path, cur.offset, final=final, max_bytes=MAX_WAKE_BYTES
        )
        if consumed == 0:
            # no complete line in the suffix: remember the size so the
            # carry is not re-read until growth (cleared above on reset)
            cur.seen = int(st.st_size)
            return (records, 0) if records else None
        records.extend(self._emit(cur, res, data))
        cur.offset += consumed
        return records, consumed

    def _emit(self, cur: FileCursor, res, data: bytes) -> list[dict]:
        """Records for one scanned suffix; advances ``cur.line`` and
        ``cur.emitted``.  Line numbers are file-global: suffix-local line
        ``k`` is global ``cur.line + k - 1`` (the cursor sits at a line
        start by construction)."""
        import numpy as np

        from distributed_grep_tpu.ops import lines as lines_mod

        nl_idx = lines_mod.newline_index(data)
        n_lines = len(nl_idx) + (0 if data.endswith(b"\n") else 1)
        matched = res.matched_lines
        if self.invert:
            matched = np.setdiff1d(
                np.arange(1, n_lines + 1, dtype=np.int64), matched
            )
        records: list[dict] = []
        selected = int(matched.size)
        if self.presence_only:
            if selected:
                records.append({"file": cur.path, "match": True})
                cur.emitted += selected
                cur.done = True
        elif self.count_only:
            if selected:
                # never materialize lines: the match-dense worst case is
                # a bandwidth-bound counter update
                records.append({"file": cur.path, "count": selected})
                cur.emitted += selected
        else:
            for ln in matched.tolist():
                # line_span's end EXCLUDES the newline — the slice is the
                # line text verbatim
                s, e = lines_mod.line_span(nl_idx, int(ln), len(data))
                text = data[s:e]
                records.append({
                    "file": cur.path,
                    "line": cur.line + int(ln) - 1,
                    # surrogateescape: arbitrary bytes round-trip through
                    # the json journal/stream exactly (the repo-wide
                    # pattern-bytes convention); display layers
                    # re-encode and replace-decode
                    "text": text.decode("utf-8", "surrogateescape"),
                })
            cur.emitted += selected
        cur.line += n_lines
        return records


# ------------------------------------------------------------ durability
class FollowLog:
    """Durable wake log in the job workdir (TaskJournal mechanics).  One
    line per (wake, file): the advanced cursor and the records it
    emitted land ATOMICALLY — replay can neither lose a line whose
    cursor advanced nor duplicate one whose advance never committed."""

    FILENAME = "follow.jsonl"
    # Startup compaction threshold: a log past this size rewrites as a
    # bounded snapshot (cursors + retained tail) at runner construction —
    # the wake stream is unbounded, the durable state it encodes is not.
    COMPACT_BYTES = 1 << 20
    # Records retained by replay (and therefore by compaction): bounds
    # restart memory no matter how long the standing query streamed.
    REPLAY_TAIL_RECORDS = 8192

    def __init__(self, path: str | Path):
        self._journal = TaskJournal(path)

    def record_wake(self, path: str, cursor: dict, seq0: int,
                    records: list[dict]) -> None:
        self._journal.record({
            "kind": "wake", "file": path, "cursor": cursor,
            "seq0": seq0, "records": records, "t": time.time(),
        })

    def close(self) -> None:
        self._journal.close()

    @staticmethod
    def replay(path: str | Path):
        """(cursors, next_seq, tail): per-file latest cursor state, the
        next record sequence number, and the last REPLAY_TAIL_RECORDS
        (seq, record) pairs in order (the caller preloads them into its
        ring — retaining the full history would make restart memory
        proportional to everything the query ever streamed).  Records
        whose seq was already assigned are SKIPPED: a wake whose journal
        line landed but whose fsync failed re-journals the same records
        under the same seq0 after the cursor rollback, and first-
        occurrence-wins keeps the ring's contiguous-seq invariant."""
        cursors: dict[str, dict] = {}
        next_seq = 1
        tail: deque = deque(maxlen=FollowLog.REPLAY_TAIL_RECORDS)
        for e in TaskJournal.replay(path):
            if e.get("kind") != "wake":
                continue
            f = e.get("file")
            if isinstance(f, str) and isinstance(e.get("cursor"), dict):
                cursors[f] = e["cursor"]
            seq = int(e.get("seq0", next_seq))
            for rec in e.get("records") or []:
                if seq >= next_seq:
                    tail.append((seq, rec))
                seq += 1
            next_seq = max(next_seq, seq)
        return cursors, next_seq, list(tail)

    @staticmethod
    def compact(path: str | Path, cursors: dict[str, dict], next_seq: int,
                tail: list[tuple[int, dict]]) -> None:
        """Rewrite the wake log as its bounded snapshot (tmp + fsync +
        rename, the registry-compaction mechanics): the retained tail in
        seq order — replayable records-only lines — then one cursor line
        per file stamped seq0=next_seq so a replay reproduces the exact
        (cursors, next_seq, tail) it was built from."""
        p = Path(path)
        tmp = p.with_name(p.name + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            for seq, rec in tail:
                f.write(json.dumps(
                    {"kind": "wake", "file": str(rec.get("file", "")),
                     "seq0": seq, "records": [rec]},
                    sort_keys=True) + "\n")
            for fp, st in cursors.items():
                f.write(json.dumps(
                    {"kind": "wake", "file": fp, "cursor": st,
                     "seq0": next_seq, "records": []},
                    sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)


# ------------------------------------------------------------ streaming
class StreamRing:
    """Bounded subscriber buffer: publish never blocks (the scan loop is
    the producer), eviction is oldest-first past the byte cap, and a
    reader whose cursor fell behind learns HOW MANY records it lost
    (the explicit ``dropped`` count) before continuing from the oldest
    retained record."""

    # Per-read response bound: a catch-up reader drains in pages instead
    # of one giant JSON body.
    MAX_READ_RECORDS = 1024

    def __init__(self, cap_bytes: int | None = None, start_seq: int = 1):
        self.cap_bytes = (
            env_stream_buffer() if cap_bytes is None else int(cap_bytes)
        )
        self._lock = lockdep.make_lock("follow-stream")
        self._cond = threading.Condition(self._lock)
        self._dq: deque = deque()  # (seq, record, approx_bytes)
        self._bytes = 0
        self.next_seq = int(start_seq)
        self._closed = False

    @staticmethod
    def _size(rec: dict) -> int:
        return 48 + sum(len(str(k)) + len(str(v)) for k, v in rec.items())

    def publish(self, records: list[dict]) -> int:
        """Append records (assigning sequence numbers), shed oldest past
        the cap.  Returns the first assigned seq."""
        if not records:
            return self.next_seq
        dropped = 0
        with self._cond:
            seq0 = self.next_seq
            for rec in records:
                sz = self._size(rec)
                self._dq.append((self.next_seq, rec, sz))
                self._bytes += sz
                self.next_seq += 1
            while self._bytes > self.cap_bytes and len(self._dq) > 1:
                _seq, _rec, sz = self._dq.popleft()
                self._bytes -= sz
                dropped += 1
            self._cond.notify_all()
        if dropped:
            _count("stream_dropped_records", dropped)
        return seq0

    def read_since(self, cursor: int, timeout: float = 0.0):
        """(records, next_cursor, dropped): records with seq > ``cursor``
        (each carries its ``seq``), the cursor to pass next, and how many
        records between ``cursor`` and the oldest retained one were shed
        (0 for a keeping-up consumer).  Waits up to ``timeout`` for news
        when nothing is pending (long-poll)."""
        cursor = max(0, int(cursor))
        deadline = time.monotonic() + max(0.0, timeout)
        with self._cond:
            while not self._closed:
                if self._dq and self._dq[-1][0] > cursor:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, 0.5))
            out: list[dict] = []
            dropped = 0
            nxt = cursor
            if self._dq and self._dq[-1][0] > cursor:
                first_seq = self._dq[0][0]
                if first_seq > cursor + 1:
                    dropped = first_seq - 1 - cursor
                # ring seqs are CONTIGUOUS (publish appends consecutive
                # seqs, shed pops the head, preload seeds a journal tail
                # whose wake lines assigned them consecutively), so the
                # page start is arithmetic — never a scan of the ring
                start = max(0, cursor + 1 - first_seq)
                for seq, rec, _sz in itertools.islice(
                    self._dq, start, start + self.MAX_READ_RECORDS
                ):
                    out.append({"seq": seq, **rec})
                    nxt = seq
        return out, nxt, dropped

    def preload(self, tail: list[tuple[int, dict]]) -> None:
        """Seed the ring from a replayed journal tail (restart path): the
        records keep their original sequence numbers; anything beyond
        the byte cap sheds oldest-first exactly like a live publish —
        but WITHOUT counting into stream_dropped_records (nothing was
        dropped; the full history stays in the journal)."""
        with self._cond:
            for seq, rec in tail:
                if seq >= self.next_seq:
                    continue  # replay seeded next_seq past the tail
                sz = self._size(rec)
                self._dq.append((seq, rec, sz))
                self._bytes += sz
            while self._bytes > self.cap_bytes and len(self._dq) > 1:
                _seq, _rec, sz = self._dq.popleft()
                self._bytes -= sz

    def close(self) -> None:
        """Wake every long-polling reader (daemon stop / job cancel)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()


# --------------------------------------------------------------- runner
class FollowRunner:
    """One daemon-side standing query: engine + scanner + wake loop +
    durable log + subscriber ring.  Constructed OUTSIDE the service lock
    (journal open + log replay are filesystem work — the _flush_starts
    contract); the engine builds lazily on the runner thread (model
    compile can take seconds; on a chip, the first XLA compile 20-40 s).

    Ordering per wake and file: journal line FIRST (fsync), ring publish
    second — durability before visibility, so a crash between the two
    re-serves the already-durable records from the replayed tail instead
    of losing them."""

    def __init__(self, job_id: str, config, work_root: str | Path, *,
                 event_log=None, on_fail=None, write_gate=None):
        self.job_id = job_id
        self.config = config
        self.event_log = event_log
        self.on_fail = on_fail
        # Daemon-scope write fence (round 18 HA failover): consulted
        # before each wake's journal writes.  A False answer means this
        # daemon lost the work-root lease — the wake is ABANDONED before
        # any cursor advances or record publishes (the promoted daemon
        # resumed the standing query from follow.jsonl; a stale append
        # would corrupt ITS cursor replay) and the loop stops.  None
        # (single-daemon) skips the check entirely.
        self.write_gate = write_gate
        self.poll_s = env_follow_poll_s(
            float(config.follow_poll_s or DEFAULT_FOLLOW_POLL_S)
        )
        self._log_path = Path(work_root) / FollowLog.FILENAME
        cursors, next_seq, tail = FollowLog.replay(self._log_path)
        self._resume_cursors = cursors
        self.resumed = bool(cursors)
        self.ring = StreamRing(start_seq=next_seq)
        # preload the durable tail so a subscriber reconnecting across a
        # restart continues from its cursor without a gap (older records
        # beyond the cap shed exactly like a slow consumer's)
        self.ring.preload(tail)
        try:
            if (self._log_path.exists()
                    and self._log_path.stat().st_size
                    > FollowLog.COMPACT_BYTES):
                # the wake stream is unbounded; its durable state is not —
                # rewrite the log as the snapshot replay just produced
                # (disk stays bounded, the NEXT restart replays in O(tail))
                FollowLog.compact(self._log_path, cursors, next_seq, tail)
        except OSError:
            log.exception("follow log compaction failed for %s", job_id)
        self._log = FollowLog(self._log_path)
        self._log_dirty = False
        self._scanner: FollowScanner | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.wakes = 0
        self.error = ""
        self.started_at = time.time()

    # -- engine construction (lazy: ops stack imports live here only) ----
    def _build_scanner(self) -> FollowScanner:
        from distributed_grep_tpu.ops.engine import cached_engine

        opts = dict(self.config.effective_app_options())
        patterns = opts.get("patterns")
        pattern = opts.get("pattern") if patterns is None else None
        if isinstance(pattern, bytes):
            pattern = pattern.decode("utf-8", "surrogateescape")
        engine, _verdict = cached_engine(
            pattern,
            patterns=list(patterns) if patterns is not None else None,
            ignore_case=bool(opts.get("ignore_case", False)),
            # host scanning by default: the daemon's standing queries are
            # latency-bound small suffixes; "device" opts in explicitly
            backend=("device" if opts.get("backend") == "device" else "cpu"),
        )
        scanner = FollowScanner(
            engine, list(self.config.input_files),
            invert=bool(opts.get("invert", False)),
            count_only=bool(opts.get("count_only", False)),
            presence_only=bool(opts.get("presence_only", False)),
        )
        scanner.restore(self._resume_cursors)
        return scanner

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"follow-{self.job_id}"
        )
        self._thread.start()

    def request_stop(self) -> None:
        """Pure state (safe under any lock): the loop exits at its next
        wake check; readers wake via ring.close()."""
        self._stop.set()

    def close(self, join_timeout_s: float = 10.0) -> None:
        """Teardown outside every service lock: stop the loop, wake the
        subscribers, close the log.  Safe from the runner thread itself
        (the engine-build-failure path: on_fail → service close flush
        runs ON this thread — joining it would raise and skip the log
        close below)."""
        self._stop.set()
        self.ring.close()
        if (self._thread is not None
                and self._thread is not threading.current_thread()):
            self._thread.join(timeout=join_timeout_s)
        try:
            self._log.close()
        except Exception:  # noqa: BLE001 — teardown must not raise
            log.exception("follow log close failed for %s", self.job_id)

    def _run(self) -> None:
        if self._stop.is_set():
            return  # cancelled between publish and start: skip the build
        try:
            self._scanner = self._build_scanner()
        except Exception as e:  # noqa: BLE001 — bad query, healthy daemon
            log.exception("follow job %s failed to build its engine",
                          self.job_id)
            self.error = str(e)
            self.ring.close()
            if self.on_fail is not None:
                self.on_fail(self.job_id, str(e))
            return
        while not self._stop.is_set():
            try:
                self.wake_once()
            except Exception:  # noqa: BLE001 — one bad wake must not kill
                # the standing query (the file may reappear/recover)
                log.exception("follow wake failed for %s", self.job_id)
            self._stop.wait(self.poll_s)

    def wake_once(self) -> int:
        """One wake: scan, journal, publish.  Returns records emitted
        (tests and the benchmark drive this directly)."""
        if self.write_gate is not None and not self.write_gate():
            # deposed: no scan, no journal line, no publish — and no
            # further wakes (request_stop is pure state, safe here)
            self.request_stop()
            return 0
        if self._scanner is None:
            self._scanner = self._build_scanner()
        if self._log_dirty:
            # a failed journal write may have torn a line mid-file; a
            # plain append would glue the next record onto the fragment
            # and make replay discard everything after it — reopen first
            # (the TaskJournal constructor truncates the torn tail)
            try:
                self._log.close()
            except Exception:  # noqa: BLE001 — the handle may be dead
                log.exception("follow log close-for-reopen failed")
            self._log = FollowLog(self._log_path)
            self._log_dirty = False
        # pre-wake cursor snapshot: a journal write failing mid-loop
        # (disk-full blip) must roll the NOT-yet-journaled groups'
        # in-memory cursors back, or the next wake would scan past lines
        # nobody ever saw — the live no-lost-line half of the contract
        # (the journaled groups keep their advance; restart replays the
        # same state either way)
        snap = {p: c.state() for p, c in self._scanner.cursors.items()}
        groups = self._scanner.poll_once()
        emitted = 0
        for i, (path, records, cursor) in enumerate(groups):
            seq0 = self.ring.next_seq
            # durability before visibility (and the cursor advance rides
            # the SAME fsync'd line as its records — the no-dup/no-loss
            # restart argument)
            try:
                self._log.record_wake(path, cursor, seq0, records)
            except Exception:
                self._log_dirty = True  # reopen before the next append
                for p2, _recs2, _cur2 in groups[i:]:
                    c2 = self._scanner.cursors.get(p2)
                    if c2 is not None and p2 in snap:
                        c2.restore(snap[p2])
                raise
            self.ring.publish(records)
            emitted += len(records)
        if groups:
            self.wakes += 1
            if self.event_log is not None:
                try:
                    self.event_log.write({
                        "t": "instant", "name": "follow:wake",
                        "cat": "follow", "ts": time.time(),
                        "job": self.job_id,
                        "args": {"files": len(groups), "records": emitted},
                    })
                except Exception:  # noqa: BLE001 — telemetry only
                    log.exception("follow:wake event write failed")
        return emitted

    def status(self) -> dict:
        out: dict = {
            "poll_s": self.poll_s,
            "wakes": self.wakes,
            "files": len(self.config.input_files),
            "next_seq": self.ring.next_seq,
        }
        if self.resumed:
            out["resumed"] = True
        if self.error:
            out["error"] = self.error
        sc = self._scanner
        if sc is not None:
            out["selected"] = int(
                sum(c.emitted for c in sc.cursors.values())
            )
        return out

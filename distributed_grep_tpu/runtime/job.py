"""In-process job driver: coordinator + N worker threads, one call.

The single-process equivalent of launching coordinator_launch + worker_launch
binaries (main/coordinator_launch.go:11-23, main/worker_launch.go:11-19) —
the correctness spine used by tests and the local CLI, and the shape the
6.824-style integration tests run in.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from pathlib import Path

from distributed_grep_tpu.apps.loader import LoadedApplication, load_application
from distributed_grep_tpu.runtime.journal import TaskJournal
from distributed_grep_tpu.runtime.scheduler import Scheduler
from distributed_grep_tpu.runtime.transport import LocalTransport
from distributed_grep_tpu.runtime.worker import WorkerKilled, WorkerLoop
from distributed_grep_tpu.utils import spans as spans_mod
from distributed_grep_tpu.utils import trace
from distributed_grep_tpu.utils.config import JobConfig
from distributed_grep_tpu.utils.io import WorkDir
from distributed_grep_tpu.utils.logging import get_logger
from distributed_grep_tpu.utils.metrics import Metrics

log = get_logger("job")

# The grep applications' key shape (apps/grep.py map_fn) — end-anchored so
# values containing " (line number #" can't confuse parsing.  The single
# definition every output mode (sorted_lines, -c/-l/-o in __main__) shares.
import re as _re

GREP_KEY_RE = _re.compile(r"^(.*) \(line number #(\d+)\)$")


def grep_key_sort(item: tuple[str, str]):
    """Sort key for (key, value) result items: grep-style keys order by
    (file, line number); anything else lexicographically."""
    m = GREP_KEY_RE.match(item[0])
    return (m.group(1), int(m.group(2))) if m else (item[0], 0)


_GREP_KEY_MARKER = b" (line number #"


def parse_grep_key_bytes(key: bytes) -> tuple[bytes, int] | None:
    """(path_bytes, lineno) for a grep-shaped key, or None — the
    byte-level twin of GREP_KEY_RE with EXACTLY its accept semantics
    (isdigit: no sign/underscore/whitespace forms int() would take).
    One definition shared by every bytes-mode output pass."""
    i = key.rfind(_GREP_KEY_MARKER)
    if i < 0 or not key.endswith(b")"):
        return None
    digits = key[len(_GREP_KEY_MARKER) + i : -1]
    if not digits.isdigit():
        return None
    return key[:i], int(digits)


@dataclass
class JobResult:
    """Job outputs.  Results are backed by the workdir's mr-out-* files
    (the durable artifact, like the reference's /tmp/mr-data outputs) and
    read lazily/streamingly — consume them before clearing or reusing the
    work_dir."""

    output_files: list[Path]
    metrics: dict = field(default_factory=dict)
    _results: dict | None = None
    # True when every output file is already in (file, line) display order
    # (identity-reduce jobs — the grep apps — whose reduce collates via
    # runtime/columnar.IdentityCollator): collation is then a streamed
    # k-way merge instead of a second external sort (round-4 VERDICT
    # item 7; the reference sorts once, worker.go:161-169).
    fileline_sorted: bool = False

    # Materializing guard: .results on a match-dense job would silently
    # un-do the runtime's bounded-memory story at the last step, so past
    # this much on-disk output it refuses loudly instead (the 100 GB
    # north star's attractive-nuisance fix — VERDICT r3 weak #6).
    RESULTS_MATERIALIZE_LIMIT = 256 << 20

    @property
    def results(self) -> dict:
        """Merged key -> value dict (lazy; materializes ALL output in RAM).
        Refuses beyond RESULTS_MATERIALIZE_LIMIT of output — match-dense
        consumers must stream via iter_results / iter_results_sorted."""
        if self._results is None:
            total = sum(p.stat().st_size for p in self.output_files)
            if total > self.RESULTS_MATERIALIZE_LIMIT:
                raise RuntimeError(
                    f"job output is {total >> 20} MB — .results would "
                    f"materialize it all in RAM; stream via iter_results()/"
                    f"iter_results_sorted() instead (or raise "
                    f"JobResult.RESULTS_MATERIALIZE_LIMIT explicitly)"
                )
            self._results = dict(self.iter_results())
        return self._results

    @staticmethod
    def _iter_file(path):
        """(key, value) records of one mr-out file.  Byte-mode line
        iteration: values may contain \r (or NEL/U+2028...) — text mode
        would universal-newline translate or fragment records there."""
        with open(path, "rb") as f:
            for raw in f:
                line = raw.decode("utf-8", "surrogateescape").rstrip("\n")
                if line:
                    k, _, v = line.partition("\t")
                    yield k, v

    def iter_results(self):
        """Stream (key, value) records from the mr-out-* files, file order,
        O(1) memory.  Keys never span partitions (each key hashes to one
        reduce task) so no cross-file dedup is needed."""
        for path in self.output_files:
            yield from self._iter_file(path)

    def iter_results_sorted(self, memory_bytes: int = 64 << 20,
                            spill_dir: str | None = None):
        """Stream (key, value) in grep_key_sort order with BOUNDED memory.

        The mr-out-* files are lexicographically key-sorted per partition,
        which is NOT (file, numeric line) order — "#10" sorts before "#9" —
        so a plain k-way merge cannot produce the CLI's output order.
        Instead the stream re-sorts through the reduce side's own external
        sorter (runtime/extsort.py): records spill to disk past
        ``memory_bytes``, so a match-dense job no longer un-does the
        reduce side's boundedness at collation time (VERDICT r2 item 6).
        The sort key is the grep_key_sort tuple encoded order-isomorphically
        (path + NUL + zero-padded line number; NUL sorts below every path
        byte, preserving prefix order).

        ``fileline_sorted`` jobs (identity-reduce — every output file
        already in display order) skip the sort entirely: a k-way heap
        merge over the per-file streams, one record resident per file."""
        if self.fileline_sorted:
            import heapq

            def keyed(path):
                for k, v in self._iter_file(path):
                    yield grep_key_sort((k, v)), k, v

            for _, k, v in heapq.merge(
                *(keyed(p) for p in self.output_files)
            ):
                yield k, v
            return
        import json as _json

        from distributed_grep_tpu.apps.base import KeyValue
        from distributed_grep_tpu.runtime.extsort import ExternalReducer

        def encode(k: str) -> str:
            m = GREP_KEY_RE.match(k)
            if m:
                return f"{m.group(1)}\x00{int(m.group(2)):020d}"
            return f"{k}\x00{0:020d}"

        with ExternalReducer(memory_limit_bytes=memory_bytes,
                             spill_dir=spill_dir) as sorter:
            sorter.add_many(
                KeyValue(encode(k), _json.dumps([k, v]))
                for k, v in self.iter_results()
            )
            for _, payload in sorter.merged():
                k, v = _json.loads(payload)
                yield k, v

    def iter_grep_keys(self):
        """(path, lineno) per grep-shaped record, allocation-light: bytes
        parse (no regex, no value decode) with the path string cached
        across consecutive records of the same file — the -o/-b/context
        modes' set-building pre-pass over match-dense output."""
        last_raw: bytes | None = None
        last_path: str | None = None
        for out in self.output_files:
            with open(out, "rb") as f:
                for raw in f:
                    line = raw.rstrip(b"\n")
                    if not line:
                        continue
                    tab = line.find(b"\t")
                    key = line[:tab] if tab >= 0 else line
                    parsed = parse_grep_key_bytes(key)
                    if parsed is None:
                        continue  # not a grep-shaped key
                    pb, ln = parsed
                    if pb != last_raw:
                        last_raw = pb
                        last_path = pb.decode("utf-8", "surrogateescape")
                    yield last_path, ln

    def _iter_records_bytes_sorted(self):
        """((path_str, lineno), line_bytes, tab_index) in display order —
        the ONE bytes-mode record merge every fast output path builds on.
        The merge key uses the DECODED path (cached across consecutive
        records of a file, so the decode runs per file change, not per
        record): the per-file streams were sorted by the collator under
        grep_key_sort's STR ordering, and a bytes-keyed merge would
        silently misorder exotic filenames where surrogateescape
        codepoint order diverges from UTF-8 byte order (round-5 review).
        Requires ``fileline_sorted``."""
        import heapq

        if not self.fileline_sorted:
            raise RuntimeError(
                "bytes-mode record streams need fileline_sorted outputs"
            )

        def keyed(path):
            last_pb = None
            last_p = None
            with open(path, "rb") as f:
                for raw in f:
                    line = raw.rstrip(b"\n")
                    if not line:
                        continue
                    tab = line.find(b"\t")
                    key = line[:tab] if tab >= 0 else line
                    parsed = parse_grep_key_bytes(key)
                    if parsed is None:
                        k = (key.decode("utf-8", "surrogateescape"), 0)
                    else:
                        pb, ln = parsed
                        if pb != last_pb:
                            last_pb = pb
                            last_p = pb.decode("utf-8", "surrogateescape")
                        k = (last_p, ln)
                    yield k, line, tab

        return heapq.merge(
            *(keyed(p) for p in self.output_files), key=lambda t: t[0]
        )

    def iter_grep_records_bytes(self):
        """((path_str, lineno) — lineno 0 for non-grep-shaped keys —,
        value_bytes) in display order: the -o mode's bytes stream (the
        match regex then runs over the raw line bytes, GNU's C-locale
        semantics for -i)."""
        for k, line, tab in self._iter_records_bytes_sorted():
            yield k, (line[tab + 1 :] if tab >= 0 else b"")

    def iter_display_bytes_sorted(self):
        """Final display lines (``b"<key> <value>\\n"``) in (file, line)
        order — the match-dense CLI print path: bytes in, bytes out
        (non-UTF8 filename bytes pass through verbatim, like GNU grep's
        output)."""
        for _k, line, _tab in self._iter_records_bytes_sorted():
            yield line.replace(b"\t", b" ", 1) + b"\n"

    # Output totals up to this size may take the vectorized whole-buffer
    # display merge.  Peak transient memory is a small MULTIPLE of the
    # output (the joined buffer, the per-line prefix/digit windows, the
    # int64 gather index at 8 bytes/output byte, and the final slab —
    # intermediates are freed as the pass proceeds), so the cap is set
    # well below RESULTS_MATERIALIZE_LIMIT; larger jobs keep the
    # O(1)-memory record merge.
    DISPLAY_VECTOR_CAP = 128 << 20

    def display_blocks_sorted(self):
        """Display output as bytes BLOCKS in (file, line) order — same
        bytes as iter_display_bytes_sorted joined, bigger pieces.

        Fast path (round 6): when total output fits DISPLAY_VECTOR_CAP,
        the merge runs natively (libdgrep dgrep_merge_display — a k-way
        merge over the pre-sorted mr-out buffers with the Python merge's
        exact ordering, surrogateescape-codepoint path compare included;
        multi-file jobs take it too).  A job with any non-grep-shaped
        record, or without libdgrep, falls to the round-5 vectorized
        single-path pass, then to the streaming record merge — all three
        produce identical bytes."""
        total = sum(p.stat().st_size for p in self.output_files)
        if 0 < total <= self.DISPLAY_VECTOR_CAP:
            from distributed_grep_tpu.utils import native

            # availability gated BEFORE reading: a no-native install must
            # not materialize the whole output set just to fall back
            if self.fileline_sorted and native.merge_display_available():
                block = native.merge_display(
                    [p.read_bytes() for p in self.output_files]
                )
                if block is not None:
                    if block:
                        yield block
                    return
            block = self._single_path_display_block()
            if block is not None:
                yield block
                return
        yield from self.iter_display_bytes_sorted()

    def _single_path_display_block(self):
        """The vectorized single-file display merge, or None when the
        output is not single-path grep-shaped (caller falls back)."""
        import numpy as np

        from distributed_grep_tpu.ops.lines import newline_index
        from distributed_grep_tpu.runtime.columnar import gather_ranges

        parts = [p.read_bytes() for p in self.output_files]
        # EVERY file must be newline-terminated, or concatenation would
        # fuse a record across the file boundary into one silently
        # corrupt line (round-5 review) — the reduce writer always
        # terminates lines, so a violation means foreign output: fall
        # back to the per-file record merge.
        if any(part and not part.endswith(b"\n") for part in parts):
            return None
        buf = b"".join(parts)
        del parts
        if not buf:
            return None
        arr = np.frombuffer(buf, dtype=np.uint8)
        nl = newline_index(buf).astype(np.int64)
        if nl.size == 0:
            return None
        starts = np.concatenate(([0], nl[:-1] + 1)).astype(np.int64)
        ends = nl  # exclusive of '\n'
        keep = ends > starts  # drop empty lines
        starts, ends = starts[keep], ends[keep]
        if not starts.size:
            return None
        # the common prefix "path (line number #" from the first record
        first = buf[int(starts[0]) : int(ends[0])]
        tab = first.find(b"\t")
        parsed = parse_grep_key_bytes(first[:tab] if tab >= 0 else first)
        if parsed is None:
            return None
        prefix = parsed[0] + _GREP_KEY_MARKER
        plen = len(prefix)
        if np.any(ends - starts < plen + 2):
            return None  # some line cannot even hold prefix + digit + ')'
        # every line must carry the SAME prefix (single-input job)
        win = arr[starts[:, None] + np.arange(plen)]
        prefix_ok = (win == np.frombuffer(prefix, np.uint8)).all()
        del win
        if not prefix_ok:
            return None
        # parse line numbers: up to 19 digit bytes after the prefix
        MAXD = 19
        dwin = arr[
            np.minimum(starts[:, None] + plen + np.arange(MAXD), arr.size - 1)
        ]
        isdig = (dwin >= 48) & (dwin <= 57)
        # digits run from column 0; first non-digit column per row
        ndig = np.where(
            isdig.all(axis=1), MAXD, np.argmin(isdig, axis=1)
        ).astype(np.int64)
        if np.any(ndig == 0) or np.any(ndig >= MAXD):
            return None
        # the byte after the digits must be ')' then '\t'
        after = starts + plen + ndig
        if not (
            (arr[np.minimum(after, arr.size - 1)] == 0x29).all()
            and (arr[np.minimum(after + 1, arr.size - 1)] == 0x09).all()
        ):
            return None
        linenos = np.zeros(starts.size, dtype=np.int64)
        for k in range(int(ndig.max())):
            active = ndig > k
            linenos[active] = (
                linenos[active] * 10 + (dwin[active, k].astype(np.int64) - 48)
            )
        del dwin, isdig
        order = np.argsort(linenos, kind="stable")
        slab, offsets = gather_ranges(
            arr, starts[order], ends[order] + 1  # include the '\n'
        )
        out = np.frombuffer(slab, dtype=np.uint8).copy()
        # the one '\t' per line sits right after "...#<digits>)"
        tab_pos = offsets[:-1] + plen + ndig[order] + 1
        out[tab_pos] = 0x20
        return out.tobytes()

    def sorted_lines(self) -> list[str]:
        """Output lines sorted naturally: grep-style keys sort by (file, line
        number); anything else sorts lexicographically."""
        return [f"{k} {v}" for k, v in self.iter_results_sorted()]


def plan_map_splits(
    input_files: list[str],
    batch_bytes: int,
    small_bytes: int | None = None,
    pruner=None,
) -> list:
    """Group consecutive small input files into multi-file map splits —
    MapReduce's batch-small-inputs-into-splits move (Dean & Ghemawat §3.1)
    applied to the grep -r regime: one map task (and, through
    GrepEngine.scan_batch, one packed device dispatch per window) covers
    many sub-threshold files instead of each paying a task + dispatch.

    Returns a mixed list the Scheduler accepts: plain paths for files at
    or above ``small_bytes`` (they keep their own task — and the
    streaming map_path_fn), lists of paths for batched groups whose
    packed size fits ``batch_bytes``.  Consecutive-only grouping keeps
    the plan deterministic and the members in input (display) order.
    ``batch_bytes`` <= 0 disables grouping; ``small_bytes`` defaults to
    the engine's device_min_bytes default (DGREP_DEVICE_MIN_BYTES or
    1 MB) so "too small for its own dispatch" means the same thing on
    both sides.

    ``pruner`` (index.plan.SplitPruner, shard-index tier) drops files
    whose persisted trigram summary proves the query cannot match —
    pruned files never become (part of) a map task, so no worker ever
    opens or dispatches them.  The caller (runtime/service) gates the
    pruner on app semantics where a zero-match file still produces
    output (invert/count/presence jobs plan unpruned), and its summary
    lookups revalidate fresh stats, so a drifted file is a clean miss
    that keeps its task."""
    import os

    if pruner is not None:
        input_files = [f for f in input_files if not pruner.prune(f)]
    if batch_bytes <= 0 or len(input_files) < 2:
        return list(input_files)
    if small_bytes is None:
        # the engine's small-input bound, parsed the ONE way both readers
        # share (ops/layout.env_device_min_bytes)
        from distributed_grep_tpu.ops.layout import env_device_min_bytes

        small_bytes = env_device_min_bytes()
    out: list = []
    group: list[str] = []
    group_bytes = 0

    def close() -> None:
        nonlocal group, group_bytes
        if group:
            out.append(group[0] if len(group) == 1 else group)
            group, group_bytes = [], 0

    for f in input_files:
        try:
            size = os.path.getsize(f)
        except OSError:
            size = None  # unreadable/vanished: keep its own task — the
            # map attempt surfaces the error exactly as it does today
        if size is None or size >= small_bytes:
            close()
            out.append(f)
            continue
        packed = size + 1  # + the possibly-synthesized '\n' terminator
        if group and group_bytes + packed > batch_bytes:
            close()
        group.append(f)
        group_bytes += packed
    close()
    return out


def collate_outputs(workdir: WorkDir) -> dict:
    """Merge all mr-out-* files into one key->value dict.  Routed through
    JobResult.results so the RESULTS_MATERIALIZE_LIMIT guard applies —
    match-dense jobs must stream via JobResult.iter_results instead."""
    return JobResult(output_files=workdir.list_outputs()).results


def run_job(
    config: JobConfig,
    n_workers: int = 2,
    app: LoadedApplication | None = None,
    resume: bool = False,
    fault_hooks_per_worker: list[dict] | None = None,
    store_faults_per_worker: list[dict] | None = None,
) -> JobResult:
    from distributed_grep_tpu.runtime.store import FaultStore, make_store

    workdir = WorkDir(
        config.work_dir,
        store=make_store(config.store, durable=config.durable),
    )
    if app is None:
        app = load_application(config.application, **config.effective_app_options())

    journal = None
    resume_entries = None
    if resume:
        if config.journal:
            resume_entries = TaskJournal.replay(workdir.journal_path())
    else:
        # Fresh job: a reused work_dir must not leak a previous job's journal,
        # intermediate files, or outputs into this one (a smaller n_reduce
        # would otherwise leave stale mr-out-* files that collate_outputs
        # would silently merge in).
        workdir.clear()
    if config.journal:
        journal = TaskJournal(workdir.journal_path())

    metrics = Metrics()
    # Span pipeline (utils/spans.py): same wiring as the HTTP coordinator —
    # the scheduler persists worker-shipped spans + its own decisions to
    # events.jsonl in the work dir; off by default (no file, no payload).
    spans_on = spans_mod.enabled(config.spans)
    event_log = (
        spans_mod.EventLog(
            workdir.root / spans_mod.EventLog.FILENAME, fresh=not resume
        )
        if spans_on else None
    )
    scheduler = Scheduler(
        files=plan_map_splits(
            list(config.input_files), config.effective_batch_bytes()
        ),
        n_reduce=config.n_reduce,
        task_timeout_s=config.task_timeout_s,
        sweep_interval_s=config.sweep_interval_s,
        app_options=config.effective_app_options(),
        journal=journal,
        resume_entries=resume_entries,
        metrics=metrics,
        commit_resolver=workdir.resolve_task_commit,
        event_log=event_log,
    )

    def worker_main(idx: int) -> None:
        hooks = (fault_hooks_per_worker or [{}] * n_workers)[idx]
        # store-level crash injection (CrashPoint hooks) wraps only THIS
        # worker's commit path; the shared workdir store stays clean for
        # the others and for the scheduler's commit resolution.
        sfaults = (store_faults_per_worker or [{}] * n_workers)[idx]
        store = FaultStore(workdir.store, sfaults) if sfaults else None
        loop = WorkerLoop(
            LocalTransport(scheduler, workdir,
                           rpc_timeout_s=config.rpc_timeout_s, store=store),
            app,
            metrics=metrics,
            fault_hooks=hooks,
            reduce_memory_bytes=config.reduce_memory_bytes,
            spill_dir=config.spill_dir or str(Path(config.work_dir) / "spill"),
            spans_enabled=spans_on,
            job_id=config.effective_job_id(),
        )
        try:
            loop.run()
        except WorkerKilled:
            log.info("worker thread %d killed by fault injection", idx)
        except Exception:
            log.exception("worker thread %d crashed", idx)

    threads = [
        threading.Thread(target=worker_main, args=(i,), name=f"worker-{i}", daemon=True)
        for i in range(n_workers)
    ]
    with trace.job_trace():
        for t in threads:
            t.start()
        # Wait for completion — but abort instead of hanging if every worker
        # has died (e.g. a config error raising in all of them) with work
        # outstanding.
        while not scheduler.wait_done(timeout=0.5):
            if all(not t.is_alive() for t in threads):
                scheduler.stop()
                raise RuntimeError(
                    "job aborted: all workers exited with tasks outstanding "
                    "(see worker logs above)"
                )
        scheduler.stop()
        for t in threads:
            t.join(timeout=10.0)
    if journal:
        scheduler.close_journal()  # drains staged completions, then closes
    if event_log is not None:
        event_log.close()

    return JobResult(
        output_files=workdir.list_outputs(),
        metrics=metrics.snapshot(),
        fileline_sorted=getattr(app.module, "reduce_is_identity", False),
    )

"""In-process job driver: coordinator + N worker threads, one call.

The single-process equivalent of launching coordinator_launch + worker_launch
binaries (main/coordinator_launch.go:11-23, main/worker_launch.go:11-19) —
the correctness spine used by tests and the local CLI, and the shape the
6.824-style integration tests run in.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from pathlib import Path

from distributed_grep_tpu.apps.loader import LoadedApplication, load_application
from distributed_grep_tpu.runtime.journal import TaskJournal
from distributed_grep_tpu.runtime.scheduler import Scheduler
from distributed_grep_tpu.runtime.transport import LocalTransport
from distributed_grep_tpu.runtime.worker import WorkerKilled, WorkerLoop
from distributed_grep_tpu.utils import trace
from distributed_grep_tpu.utils.config import JobConfig
from distributed_grep_tpu.utils.io import WorkDir
from distributed_grep_tpu.utils.logging import get_logger
from distributed_grep_tpu.utils.metrics import Metrics

log = get_logger("job")

# The grep applications' key shape (apps/grep.py map_fn) — end-anchored so
# values containing " (line number #" can't confuse parsing.  The single
# definition every output mode (sorted_lines, -c/-l/-o in __main__) shares.
import re as _re

GREP_KEY_RE = _re.compile(r"^(.*) \(line number #(\d+)\)$")


def grep_key_sort(item: tuple[str, str]):
    """Sort key for (key, value) result items: grep-style keys order by
    (file, line number); anything else lexicographically."""
    m = GREP_KEY_RE.match(item[0])
    return (m.group(1), int(m.group(2))) if m else (item[0], 0)


@dataclass
class JobResult:
    output_files: list[Path]
    results: dict[str, str]  # merged key -> value across all mr-out-* files
    metrics: dict = field(default_factory=dict)

    def sorted_lines(self) -> list[str]:
        """Output lines sorted naturally: grep-style keys sort by (file, line
        number); anything else sorts lexicographically."""
        return [f"{k} {v}" for k, v in sorted(self.results.items(), key=grep_key_sort)]


def collate_outputs(workdir: WorkDir) -> dict[str, str]:
    """Merge all mr-out-* files into one key->value dict.

    Keys never span partitions (each key hashes to exactly one reduce task),
    so the merge is a plain union.
    """
    results: dict[str, str] = {}
    for path in workdir.list_outputs():
        # Read as bytes and split on \n only: values may contain \r (or
        # \x85,  , ...) — text-mode read_text() would translate a lone
        # \r to \n (universal newlines), and splitlines() would fragment
        # the record at any of those characters.
        for line in path.read_bytes().decode("utf-8", "surrogateescape").split("\n"):
            if line:
                k, _, v = line.partition("\t")
                results[k] = v
    return results


def run_job(
    config: JobConfig,
    n_workers: int = 2,
    app: LoadedApplication | None = None,
    resume: bool = False,
    fault_hooks_per_worker: list[dict] | None = None,
) -> JobResult:
    workdir = WorkDir(config.work_dir)
    if app is None:
        app = load_application(config.application, **config.app_options)

    journal = None
    resume_entries = None
    if resume:
        if config.journal:
            resume_entries = TaskJournal.replay(workdir.journal_path())
    else:
        # Fresh job: a reused work_dir must not leak a previous job's journal,
        # intermediate files, or outputs into this one (a smaller n_reduce
        # would otherwise leave stale mr-out-* files that collate_outputs
        # would silently merge in).
        workdir.clear()
    if config.journal:
        journal = TaskJournal(workdir.journal_path())

    metrics = Metrics()
    scheduler = Scheduler(
        files=list(config.input_files),
        n_reduce=config.n_reduce,
        task_timeout_s=config.task_timeout_s,
        sweep_interval_s=config.sweep_interval_s,
        app_options=config.app_options,
        journal=journal,
        resume_entries=resume_entries,
        metrics=metrics,
    )

    def worker_main(idx: int) -> None:
        hooks = (fault_hooks_per_worker or [{}] * n_workers)[idx]
        loop = WorkerLoop(
            LocalTransport(scheduler, workdir, rpc_timeout_s=config.rpc_timeout_s),
            app,
            metrics=metrics,
            fault_hooks=hooks,
            reduce_memory_bytes=config.reduce_memory_bytes,
            spill_dir=config.spill_dir or str(Path(config.work_dir) / "spill"),
        )
        try:
            loop.run()
        except WorkerKilled:
            log.info("worker thread %d killed by fault injection", idx)
        except Exception:
            log.exception("worker thread %d crashed", idx)

    threads = [
        threading.Thread(target=worker_main, args=(i,), name=f"worker-{i}", daemon=True)
        for i in range(n_workers)
    ]
    with trace.job_trace():
        for t in threads:
            t.start()
        # Wait for completion — but abort instead of hanging if every worker
        # has died (e.g. a config error raising in all of them) with work
        # outstanding.
        while not scheduler.wait_done(timeout=0.5):
            if all(not t.is_alive() for t in threads):
                scheduler.stop()
                raise RuntimeError(
                    "job aborted: all workers exited with tasks outstanding "
                    "(see worker logs above)"
                )
        scheduler.stop()
        for t in threads:
            t.join(timeout=10.0)
    if journal:
        journal.close()

    return JobResult(
        output_files=workdir.list_outputs(),
        results=collate_outputs(workdir),
        metrics=metrics.snapshot(),
    )

"""Daemon-scope lifecycle event log — the fleet timeline (round 19).

The per-job ``events.jsonl`` records what happened INSIDE a job; nothing
records what the daemon itself decided — lease steals, promotions,
quarantine episodes, scale actions, admission 429s, lost-output
revocations.  ``DaemonLog`` writes those as one JSON line each to
``<work_root>/daemon.jsonl`` (TaskJournal mechanics: fsync per line,
torn tail truncated at reopen), shared by every daemon incarnation over
the same work root — the ``epoch`` field orders incarnations, so
``trace-export --fleet`` can render a whole failover as one timeline.

Concurrency contract (round-11 rules): event SITES run under the
service or scheduler locks, so ``stage()`` only appends to a list under
its own leaf lock (``daemon-log`` — safe under either hot lock, the
lock graph stays acyclic); ``flush()`` swaps the staged batch and
writes under the io_ok ``daemon-log-flush`` lock from UNLOCKED call
sites, re-verifying the round-18 lease write-fence after the swap — a
deposed daemon's late staged events are DROPPED, never interleaved
with the promoted daemon's records (same contract as
``_flush_registry``).

``DGREP_DAEMON_LOG=0`` is a true no-op: the serve paths construct no
DaemonLog at all (no file, no staged list); the service's hook is a
None-guarded attribute, exactly like per-job event logs.
"""

from __future__ import annotations

import os
import time
from pathlib import Path

from distributed_grep_tpu.runtime.journal import TaskJournal
from distributed_grep_tpu.utils import event_audit, lockdep
from distributed_grep_tpu.utils.logging import get_logger

log = get_logger("daemon_log")

FILENAME = "daemon.jsonl"


def env_daemon_log() -> bool:
    """DGREP_DAEMON_LOG: daemon lifecycle event log (``daemon.jsonl``)
    when serving.  Default on; ``0`` disables — no file is created and
    the event hooks are never installed."""
    return os.environ.get("DGREP_DAEMON_LOG", "").strip() != "0"


class DaemonLog:
    """Staged-flush journal of daemon lifecycle events.

    Each line: ``{"ts", "epoch", "pid", "role", "kind", "payload"}``
    (payload elided when empty).  ``epoch``/``role`` come from the
    attached lease identity (epoch 0 / role "active" for single-daemon
    deployments) and update in place at promotion/demotion via
    ``set_identity`` — events carry the identity current at STAGE time.
    """

    def __init__(self, work_root: str | Path, epoch: int = 0,
                 role: str = "active"):
        self.path = Path(work_root) / FILENAME
        self.pid = os.getpid()
        self.epoch = int(epoch)
        self.role = str(role)
        self._pending: list[dict] = []
        # Leaf staging lock: stage() is called under the service AND
        # scheduler locks (service -> daemon-log, scheduler ->
        # daemon-log are both leaf edges); the io_ok flush lock orders
        # swap + fsync'ing appends end to end, entered from unlocked
        # flush contexts only.
        self._stage_lock = lockdep.make_lock("daemon-log")
        self._flush_lock = lockdep.make_lock("daemon-log-flush",
                                             io_ok=True)
        self._journal = TaskJournal(self.path)
        self._closed = False

    # ------------------------------------------------------------- identity
    def set_identity(self, epoch: int, role: str) -> None:
        """Adopt a lease identity (promotion/demotion).  Events staged
        after this carry the new (epoch, role)."""
        self.epoch = int(epoch)
        self.role = str(role)

    # --------------------------------------------------------------- events
    def stage(self, kind: str, **payload) -> None:
        """Stage one event under the leaf lock — callable from under any
        hot lock (list append only; the fsync happens in flush())."""
        if event_audit.is_active():
            event_audit.record("daemon", kind)
        rec = {"ts": time.time(), "epoch": self.epoch, "pid": self.pid,
               "role": self.role, "kind": str(kind)}
        if payload:
            rec["payload"] = payload
        with self._stage_lock:
            self._pending.append(rec)

    def flush(self, gate=None) -> bool:
        """Write staged events outside the hot locks.  ``gate`` is the
        service's ``_write_gate()`` answer (None for single-daemon):
        consulted AFTER the swap — a fenced batch is dropped whole (the
        gate itself deposes the daemon), never partially interleaved.
        Never raises: a full disk degrades the timeline, not the
        control plane."""
        with self._flush_lock:
            with self._stage_lock:
                if not self._pending:
                    return True
                pending, self._pending = self._pending, []
            if gate is not None and not gate():
                log.warning("daemon log flush fenced: lease lost, %d "
                            "staged events dropped", len(pending))
                return False
            if self._closed:
                log.warning("daemon log closed: %d staged events dropped",
                            len(pending))
                return False
            for rec in pending:
                try:
                    self._journal.record(rec)
                except Exception:  # noqa: BLE001
                    log.exception("daemon log append failed")
        return True

    def append_now(self, kind: str, **payload) -> None:
        """Stage + flush in one call — for unlocked lifecycle sites
        (serve start/stop, lease acquire/steal, promotion) where the
        event should be durable before the next step runs."""
        self.stage(kind, **payload)
        self.flush()

    def close(self) -> None:
        self.flush()
        with self._flush_lock:
            if not self._closed:
                self._closed = True
                self._journal.close()

    def discard(self) -> None:
        """Close WITHOUT flushing — the deposed-demotion path: staged
        events are fenced anyway, and the file handle must not leak
        across an active→standby→active cycle.  No-op when already
        closed (the graceful stop path closed via the service)."""
        with self._flush_lock:
            with self._stage_lock:
                self._pending.clear()
            if not self._closed:
                self._closed = True
                self._journal.close()

    # --------------------------------------------------------------- replay
    @staticmethod
    def read(work_root: str | Path) -> list[dict]:
        """All durable events for a work root (torn tail excluded),
        epoch-then-ts ordered — the ``--fleet`` renderer's and
        ``dgrep explain``'s input.  Missing file answers []."""
        events = TaskJournal.replay(Path(work_root) / FILENAME)
        events.sort(key=lambda r: (r.get("epoch", 0), r.get("ts", 0.0)))
        return events

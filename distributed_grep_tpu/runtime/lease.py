"""Work-root lease — the active/standby election primitive (round 18).

One JSON lease file (``<work_root>/LEASE``) names the daemon currently
allowed to WRITE the work root's durable state (jobs.jsonl registry,
per-job task journals, follow logs).  The active creates it O_EXCL,
renews it on a heartbeat cadence (``DGREP_LEASE_RENEW_S``), and a
standby steals it — atomic tmp+``os.replace`` with the epoch bumped and
a FRESH random token — once the ``renewed`` stamp is stale past
``DGREP_LEASE_TTL_S``.

Ownership identity is the (epoch, token) PAIR: the epoch orders
incarnations (a revived deposed active always sees a larger epoch than
its own and demotes), the token disambiguates two same-instant stealers
(both replace; the last writer wins; the loser's re-read token
mismatches).  The lease is advisory at acquisition time but MANDATORY at
write time: every registry/journal flush batch re-verifies ownership via
``verify()`` before touching disk (the daemon-scope extension of the
round-16 zombie epoch fence), so a deposed active's late staged flush is
DROPPED, never interleaved — split-brain loses at most the one unflushed
batch, and replay stays uncorrupted.

Clock discipline: staleness compares ``time.time()`` deltas on ONE host
(active and standby share the work root's filesystem); the lease never
compares clocks across hosts.  Renewal cadence must clear the TTL with
margin — the default renew interval is ttl/3.

Lock discipline: the lease has its own small mutex (``make_lock("lease",
io_ok=True)`` — serializing lease-file I/O is its declared purpose) and
is NEVER touched under the service lock; fence checks run inside the
io_ok flush locks (registry-flush / journal-flush), i.e. in staged-flush
context only (rule ``locked-blocking``).
"""

from __future__ import annotations

import json
import os
import secrets
import threading
import time
from pathlib import Path

from distributed_grep_tpu.utils import lockdep
from distributed_grep_tpu.utils.logging import get_logger

log = get_logger("lease")

LEASE_FILENAME = "LEASE"

_DEFAULT_TTL_S = 10.0


def env_lease_ttl_s(default: float = _DEFAULT_TTL_S) -> float:
    """The ONE parser of DGREP_LEASE_TTL_S: seconds of renewal silence
    after which a lease is stealable.  Malformed or non-positive values
    fall back to the default (a zero TTL would make every lease
    instantly stealable — never what an operator means)."""
    raw = os.environ.get("DGREP_LEASE_TTL_S")
    if raw is None or raw == "":
        return default
    try:
        val = float(raw)
    except ValueError:
        return default
    return val if val > 0 else default


def env_lease_renew_s(default: float | None = None) -> float:
    """The ONE parser of DGREP_LEASE_RENEW_S: the active's renewal (and
    the standby's poll) cadence.  Default ttl/3 — three missed renewals
    before the lease goes stale."""
    raw = os.environ.get("DGREP_LEASE_RENEW_S")
    fallback = default if default is not None else env_lease_ttl_s() / 3.0
    if raw is None or raw == "":
        return fallback
    try:
        val = float(raw)
    except ValueError:
        return fallback
    return val if val > 0 else fallback


def lease_configured() -> bool:
    """True when the operator set DGREP_LEASE_TTL_S — the env-side HA
    switch (the other is ``dgrep serve --standby``).  Single-daemon
    deployments without either never create a lease file."""
    return bool(os.environ.get("DGREP_LEASE_TTL_S"))


class WorkRootLease:
    """Epoch-stamped lease file under one work root.

    States: unacquired (``epoch == 0``), held (acquire/steal succeeded,
    ``verify()`` true), lost (a later incarnation replaced the file —
    ``verify()`` false, every subsequent ``renew()`` false)."""

    def __init__(self, work_root: str | Path, *, addr: str = "",
                 ttl_s: float | None = None):
        self.work_root = Path(work_root)
        self.path = self.work_root / LEASE_FILENAME
        self.addr = addr
        self.ttl_s = float(ttl_s) if ttl_s is not None else env_lease_ttl_s()
        self.epoch = 0
        self.token = ""
        self._mutex = lockdep.make_lock("lease", io_ok=True)
        self._renew_stop: threading.Event | None = None
        self._renew_thread: threading.Thread | None = None

    # ------------------------------------------------------------- file I/O
    @staticmethod
    def read(work_root: str | Path) -> dict | None:
        """The current lease record, or None (no file / torn write).  The
        standby's poll surface; also how a standby learns the active's
        advertised address for its /status answer."""
        path = Path(work_root) / LEASE_FILENAME
        try:
            doc = json.loads(path.read_text("utf-8"))
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) else None

    def _payload(self, renewed: float) -> dict:
        return {"epoch": self.epoch, "token": self.token,
                "renewed": renewed, "addr": self.addr}

    def _write_replace(self) -> None:
        """tmp + os.replace under this lease's own path — atomic against
        concurrent stealers; readers see the old or the new record,
        never a torn one."""
        tmp = self.path.with_name(
            f".{LEASE_FILENAME}.tmp.{os.getpid()}.{self.token[:8]}")
        tmp.write_text(json.dumps(self._payload(time.time()),
                                  sort_keys=True), "utf-8")
        os.replace(tmp, self.path)

    # ------------------------------------------------------------ lifecycle
    def acquire(self) -> bool:
        """Take the lease: O_EXCL-create when absent, steal when stale.
        False when a live active holds it (the caller becomes a
        standby)."""
        with self._mutex:
            self.work_root.mkdir(parents=True, exist_ok=True)
            token = secrets.token_hex(16)
            try:
                fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_EXCL,
                             0o644)
            except FileExistsError:
                pass
            else:
                self.epoch, self.token = 1, token
                payload = json.dumps(self._payload(time.time()),
                                     sort_keys=True).encode("utf-8")
                try:
                    os.write(fd, payload)
                finally:
                    os.close(fd)
                log.info("lease acquired at %s (epoch %d)",
                         self.path, self.epoch)
                return True
            current = self.read(self.work_root)
            if current is None:
                # torn/unreadable lease: treat as stale — replace it
                stale = True
                old_epoch = 0
            else:
                stale = (time.time() - float(current.get("renewed", 0.0))
                         > self.ttl_s)
                old_epoch = int(current.get("epoch", 0))
            if not stale:
                return False
            # Steal: bump the epoch, mint a fresh token, replace
            # atomically, then RE-READ — two concurrent stealers both
            # replace; the one whose token survives won.
            self.epoch, self.token = old_epoch + 1, token
            self._write_replace()
            after = self.read(self.work_root)
            if after is None or after.get("token") != self.token:
                self.epoch, self.token = 0, ""
                return False
            log.info("lease stolen at %s (epoch %d <- stale epoch %d)",
                     self.path, self.epoch, old_epoch)
            return True

    def renew(self) -> bool:
        """Refresh the ``renewed`` stamp.  False — WITHOUT writing — when
        the on-disk record is no longer ours (a standby stole it: we are
        deposed; never clobber the winner)."""
        with self._mutex:
            if not self.token:
                return False
            current = self.read(self.work_root)
            if (current is None or current.get("token") != self.token
                    or int(current.get("epoch", -1)) != self.epoch):
                return False
            self._write_replace()
            return True

    def verify(self) -> bool:
        """The write fence: does the on-disk lease still name us?  Called
        by every registry/journal flush batch before it writes."""
        if not self.token:
            return False
        current = self.read(self.work_root)
        return (current is not None
                and current.get("token") == self.token
                and int(current.get("epoch", -1)) == self.epoch)

    def release(self) -> None:
        """Graceful handoff: delete the lease iff still ours, so a
        standby promotes immediately instead of waiting out the TTL."""
        self.stop_renewal()
        with self._mutex:
            if not self.token:
                return
            current = self.read(self.work_root)
            if (current is not None and current.get("token") == self.token):
                try:
                    self.path.unlink()
                except OSError:
                    pass
            self.epoch, self.token = 0, ""

    # -------------------------------------------------------------- renewal
    def start_renewal(self, on_lost, on_renew=None,
                      interval_s: float | None = None) -> None:
        """Daemon renewal thread: every ``interval_s`` (default
        DGREP_LEASE_RENEW_S = ttl/3) call ``renew()``; a False answer
        fires ``on_lost()`` once and stops.  ``on_renew()`` (optional)
        runs after each successful renewal — the service's worker-table
        snapshot hook rides it (satellite: a promoted daemon seeds its
        worker rows from the last pre-failover snapshot)."""
        if self._renew_thread is not None:
            return
        period = interval_s if interval_s is not None else env_lease_renew_s()
        stop = threading.Event()

        def _loop() -> None:
            while not stop.wait(period):
                if not self.renew():
                    log.warning("lease lost at %s (our epoch %d)",
                                self.path, self.epoch)
                    try:
                        on_lost()
                    except Exception:
                        log.exception("lease on_lost callback failed")
                    return
                if on_renew is not None:
                    try:
                        on_renew()
                    except Exception:
                        log.exception("lease on_renew callback failed")

        self._renew_stop = stop
        self._renew_thread = threading.Thread(
            target=_loop, name="lease-renew", daemon=True)
        self._renew_thread.start()

    def stop_renewal(self) -> None:
        if self._renew_stop is not None:
            self._renew_stop.set()
        t = self._renew_thread
        if t is not None:
            t.join(timeout=10)
        self._renew_stop = None
        self._renew_thread = None

"""Columnar record batches for the built-in grep apps' match-dense path.

The per-record pipeline (one KeyValue per matched line through emit ->
bucketize -> JSONL encode -> decode -> external sort -> collation resort)
measured ~28 us/record — a 549k-match 64 MB dense print job spent 17 s in
Python object churn around a 0.3 s scan (BASELINE.md round-4 profile), the
one workload where plain grep still beat the framework >10x end to end.

A ``LineBatch`` carries a whole chunk's matched lines as three arrays —
line numbers, a byte slab, and slab offsets — and flows through the same
pipeline stages with vectorized equivalents:

* partitioning: FNV-32a of each record's key, computed vectorized (the key
  ``"<file> (line number #N)"`` shares a per-batch prefix whose hash is
  folded once; only the line-number digits fold per record, grouped by
  digit count) — bit-identical to ``utils.native.partition`` per key, so
  the record->partition mapping is EXACTLY the per-record path's
  (reference ihash, map_reduce/worker.go:13-17);
* shuffle wire format: one header line + three binary sections per batch
  (runtime/shuffle.py embeds the blocks between ordinary JSONL records —
  old files decode unchanged);
* reduce: identity-reduce apps (the grep apps — reduce is ``values[0]``
  and keys are unique by construction) collate batches in (file, line)
  order via ``IdentityCollator`` instead of re-sorting records through
  the generic external sorter.  Output files come out ALREADY in the
  CLI's display order, so collation downstream is a streamed k-way merge
  instead of a second full external sort (round-4 VERDICT item 7: the
  reference sorts once, worker.go:161-169 — ours must not sort twice).

Custom applications never see any of this: map outputs containing only
KeyValue records take the per-record path everywhere (VERDICT item 3).
"""

from __future__ import annotations

import json
from dataclasses import dataclass

import numpy as np

from distributed_grep_tpu.apps.base import KeyValue

# Batch block marker inside intermediate files.  JSONL records always start
# with '[' (json.dumps of a [key, value] list), so a line starting with '#'
# is unambiguous.
MARKER = b"#!dgrep-colv1 "

_FNV_OFFSET = np.uint64(2166136261)
_FNV_PRIME = np.uint64(16777619)
_U32 = np.uint64(0xFFFFFFFF)


@dataclass
class LineBatch:
    """One file-chunk's matched lines, columnar.

    Logically equivalent to ``[KeyValue(f"{filename} (line number #{n})",
    text_n) for n in linenos]`` where ``text_n`` is the line's raw bytes
    (decoded utf-8/replace only at output time — the per-record path
    decodes at emit time; both produce identical output bytes).

    linenos   int64[n]    1-based line numbers, strictly increasing
    offsets   int64[n+1]  slab offsets; line i = slab[offsets[i]:offsets[i+1]]
    slab      bytes       concatenated line bytes (no separators)
    """

    filename: str
    linenos: np.ndarray
    offsets: np.ndarray
    slab: bytes

    def __len__(self) -> int:
        return int(self.linenos.size)

    @property
    def nbytes(self) -> int:
        return len(self.slab) + self.linenos.nbytes + self.offsets.nbytes

    def line_bytes(self, i: int) -> bytes:
        return self.slab[self.offsets[i] : self.offsets[i + 1]]

    def to_keyvalues(self) -> list[KeyValue]:
        """Per-record escape hatch (tests, generic consumers)."""
        return [
            KeyValue(
                key=f"{self.filename} (line number #{int(n)})",
                value=self.line_bytes(i).decode("utf-8", errors="replace"),
            )
            for i, n in enumerate(self.linenos)
        ]

    # ------------------------------------------------------------ partition
    def partitions(self, n_reduce: int) -> np.ndarray:
        """FNV-32a(key) % n_reduce per record, vectorized — bit-identical
        to utils.native.partition on the formatted key string."""
        prefix = (self.filename + " (line number #").encode(
            "utf-8", "surrogateescape"
        )
        h0 = _FNV_OFFSET
        for b in prefix:
            h0 = ((h0 ^ np.uint64(b)) * _FNV_PRIME) & _U32
        n = len(self)
        h = np.full(n, h0, dtype=np.uint64)
        v = self.linenos.astype(np.uint64)
        ndig = np.ones(n, dtype=np.int64)
        t = v // 10
        while np.any(t > 0):
            ndig += (t > 0).astype(np.int64)
            t //= 10
        for d in np.unique(ndig):
            sel = ndig == d
            vv = v[sel]
            hh = h[sel]
            for k in range(int(d)):
                digit = (vv // np.uint64(10 ** (int(d) - 1 - k))) % np.uint64(10)
                hh = ((hh ^ (digit + np.uint64(48))) * _FNV_PRIME) & _U32
            hh = ((hh ^ np.uint64(41)) * _FNV_PRIME) & _U32  # ')'
            h[sel] = hh
        return ((h & np.uint64(0x7FFFFFFF)) % np.uint64(n_reduce)).astype(
            np.int64
        )

    def select(self, mask: np.ndarray) -> "LineBatch":
        """Sub-batch of the records where ``mask`` is True (slab rebuilt
        via one vectorized gather)."""
        idx = np.flatnonzero(mask)
        starts = self.offsets[idx]
        ends = self.offsets[idx + 1]
        slab, offsets = gather_ranges(
            np.frombuffer(self.slab, dtype=np.uint8), starts, ends
        )
        return LineBatch(
            filename=self.filename,
            linenos=self.linenos[idx],
            offsets=offsets,
            slab=slab,
        )

    def split_by_partition(self, n_reduce: int) -> dict[int, "LineBatch"]:
        """Per-reduce sub-batches.  Native fast path (round 8,
        ``dgrep_build_records``): hash + partition grouping + slab gather
        run as ONE C pass over this batch's slab; the numpy fallback
        (vectorized FNV + one select/gather per partition) is
        bit-identical — partition assignment is pinned against
        ``utils.native.partition`` either way."""
        native = _native_split(
            self.filename, np.frombuffer(self.slab, dtype=np.uint8),
            self.offsets[:-1], self.offsets[1:], self.linenos, n_reduce,
        )
        if native is not None:
            return native
        parts = self.partitions(n_reduce)
        return {
            int(r): self.select(parts == r) for r in np.unique(parts)
        }

    # -------------------------------------------------------------- output
    def texts(self) -> list[str]:
        """Per-line decoded text (utf-8/replace), batched: ASCII slabs
        (the overwhelmingly common case) slice the one decoded string by
        the same offsets; anything else decodes per line."""
        if self.slab.isascii():
            s = self.slab.decode("ascii")
            off = self.offsets
            return [s[off[i] : off[i + 1]] for i in range(len(self))]
        return [
            self.line_bytes(i).decode("utf-8", errors="replace")
            for i in range(len(self))
        ]

    def format_lines(self, sep: str = "\t") -> str:
        """The mr-out text form — ``"<file> (line number #N)<sep><text>\\n"``
        per record, one joined string (the reduce-side writer)."""
        head = f"{self.filename} (line number #"
        return "".join(
            f"{head}{n}){sep}{t}\n"
            for n, t in zip(self.linenos.tolist(), self.texts())
        )

    def format_lines_bytes(self, sep: str = "\t") -> bytes:
        """``format_lines`` as the BYTES the reduce writer lands on disk
        (utf-8/surrogateescape-encoded) — native one-pass formatter when
        libdgrep is available and the slab is strictly valid UTF-8 (then
        the Python path's utf-8/replace decode is the identity and the
        native copy is byte-equal); anything else takes the Python path."""
        from distributed_grep_tpu.utils.native import format_batch

        prefix = (self.filename + " (line number #").encode(
            "utf-8", "surrogateescape"
        )
        out = format_batch(
            prefix, self.linenos, self.offsets, self.slab,
            sep.encode("ascii"),
        )
        if out is not None:
            return out
        return self.format_lines(sep).encode("utf-8", "surrogateescape")


def gather_ranges(
    arr: np.ndarray, starts: np.ndarray, ends: np.ndarray
) -> tuple[bytes, np.ndarray]:
    """Concatenate arr[starts[i]:ends[i]] for all i.  Native memcpy loop
    when libdgrep is available (the numpy cumsum-index gather below moves
    ~10 bytes of index traffic per output byte — it was the dense job's
    single hottest host pass, BASELINE.md round 6); the numpy fallback is
    bit-identical.  Returns (slab bytes, int64 offsets[n+1])."""
    from distributed_grep_tpu.utils.native import gather_ranges_native

    starts = np.asarray(starts, dtype=np.int64)
    ends = np.asarray(ends, dtype=np.int64)
    lens = ends - starts
    offsets = np.zeros(starts.size + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    total = int(offsets[-1])
    if total == 0:
        return b"", offsets
    slab = gather_ranges_native(arr, starts, ends, offsets, total)
    if slab is not None:
        return slab, offsets
    # idx[j] = delta of the source index at output byte j: +1 within a
    # range, and at each range head a jump from the previous range's last
    # byte to this range's start.  Empty ranges contribute no output
    # bytes, so they are dropped before the head positions are computed
    # (their heads would collide with the next range's).
    ne = np.flatnonzero(lens > 0)
    s, l = starts[ne], lens[ne]
    idx = np.ones(total, dtype=np.int64)
    idx[0] = s[0]
    if ne.size > 1:
        heads = offsets[ne[1:]]  # output position where each range begins
        idx[heads] = s[1:] - (s[:-1] + l[:-1] - 1)
    src = np.cumsum(idx)
    return arr[src].tobytes(), offsets


def line_spans(
    linenos: np.ndarray, nl_index: np.ndarray, n_bytes: int
) -> tuple[np.ndarray, np.ndarray]:
    """[start, end) byte span per 1-based line — the vectorized form of
    ops/lines.line_span (end excludes the '\\n').  Native single loop when
    libdgrep is available; the numpy fallback is identical (including the
    clip semantics on the unselected np.where branch)."""
    from distributed_grep_tpu.utils.native import line_spans_native

    ln = np.asarray(linenos, dtype=np.int64)
    if ln.size == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z.copy()
    sp = line_spans_native(nl_index, ln, n_bytes)
    if sp is not None:
        return sp
    nl = nl_index.astype(np.int64)
    if nl.size == 0:  # chunk with no newline: only line 1 exists
        return (np.zeros(ln.size, dtype=np.int64),
                np.full(ln.size, n_bytes, dtype=np.int64))
    # np.where evaluates both branches: clip the fancy indexes so the
    # out-of-range side (line 1 / last line) reads a harmless slot
    starts = np.where(ln == 1, 0, nl[np.clip(ln - 2, 0, nl.size - 1)] + 1)
    ends = np.where(
        ln - 1 < nl.size, nl[np.clip(ln - 1, 0, nl.size - 1)], n_bytes
    )
    return starts.astype(np.int64), ends.astype(np.int64)


def _native_split(
    filename: str, data: np.ndarray, starts: np.ndarray, ends: np.ndarray,
    stored_linenos: np.ndarray, n_reduce: int,
) -> "dict[int, LineBatch] | None":
    """The native one-pass record build (utils/native.build_records)
    wrapped into per-partition LineBatches, or None when unavailable —
    the ONE routing point both split paths (built batch, deferred batch)
    share, so the key-prefix encoding cannot drift between them."""
    from distributed_grep_tpu.utils.native import build_records

    prefix = (filename + " (line number #").encode("utf-8", "surrogateescape")
    parts = build_records(data, starts, ends, stored_linenos, prefix, n_reduce)
    if parts is None:
        return None
    return {
        p: LineBatch(filename=filename, linenos=ln, offsets=off, slab=slab)
        for p, (ln, off, slab) in parts.items()
    }


class DeferredBatch(LineBatch):
    """A LineBatch whose offsets/slab are built ON DEMAND from the source
    buffer + newline index (round 8).  The built-in grep apps emit these
    from whole-buffer scans (apps/grep_tpu._records_for and the
    single-chunk streaming leg): the worker's shuffle then splits them by
    partition straight from the SOURCE bytes in one native pass
    (``dgrep_build_records``), so the intermediate whole-batch slab
    gather never runs on the hot path.  Any other access — to_keyvalues,
    select, the wire encoder, tests — touches ``.offsets``/``.slab``
    and materializes the ordinary batch lazily, so every existing
    LineBatch consumer works unchanged (``isinstance`` included).

    Holds a reference to the source buffer: emit ONLY where that buffer
    is alive for the record's lifetime anyway (a whole-bytes map split,
    or a streamed file that fits one chunk).  The multi-chunk streaming
    path keeps eager batches — deferring there would pin every chunk's
    buffer until the shuffle leg, unbounding the stream's memory."""

    def __init__(self, filename: str, linenos: np.ndarray, data: np.ndarray,
                 nl_index: np.ndarray, n_bytes: int, lineno_base: int = 0):
        ln = np.asarray(linenos, dtype=np.int64)
        self.filename = filename
        self.linenos = ln + lineno_base  # the STORED (key) numbers
        self._local = ln
        self._base = int(lineno_base)
        self._data = data
        self._nl = nl_index
        self._n_bytes = int(n_bytes)
        self._built: LineBatch | None = None

    def _materialized(self) -> LineBatch:
        if self._built is None:
            self._built = make_batch_from_lines(
                self.filename, self._local, self._data, self._nl,
                self._n_bytes, lineno_base=self._base,
            )
        return self._built

    @property
    def offsets(self) -> np.ndarray:  # type: ignore[override]
        return self._materialized().offsets

    @property
    def slab(self) -> bytes:  # type: ignore[override]
        return self._materialized().slab

    def split_by_partition(self, n_reduce: int) -> dict[int, LineBatch]:
        from distributed_grep_tpu.utils.native import native_records_available

        if native_records_available():
            # availability gated FIRST: the span pass below exists only
            # to feed the native build — on the fallback tree it would
            # be computed, discarded, and recomputed by materialize
            starts, ends = line_spans(self._local, self._nl, self._n_bytes)
            native = _native_split(
                self.filename, self._data, starts, ends, self.linenos,
                n_reduce,
            )
            if native is not None:
                return native
        return self._materialized().split_by_partition(n_reduce)


def make_batch_from_lines(
    filename: str,
    linenos: np.ndarray,
    data: np.ndarray,
    nl_index: np.ndarray,
    n_bytes: int,
    lineno_base: int = 0,
) -> LineBatch:
    """Build a LineBatch for 1-based ``linenos`` of ``data`` (uint8 view)
    using its newline index — the vectorized form of ops/lines.line_span
    per line (end excludes the '\\n').  ``lineno_base`` shifts the STORED
    line numbers (file-global numbering for a chunk of a streamed file);
    spans are computed from the local numbers."""
    ln = np.asarray(linenos, dtype=np.int64)
    if ln.size == 0:
        return LineBatch(
            filename=filename, linenos=ln,
            offsets=np.zeros(1, dtype=np.int64), slab=b"",
        )
    starts, ends = line_spans(ln, nl_index, n_bytes)
    slab, offsets = gather_ranges(data, starts, ends)
    return LineBatch(
        filename=filename, linenos=ln + lineno_base, offsets=offsets,
        slab=slab,
    )


# ------------------------------------------------------------- wire format

def encode_batch(b: LineBatch) -> bytes:
    header = MARKER + json.dumps(
        {"file": b.filename, "n": len(b), "slab": len(b.slab)},
        ensure_ascii=False,
    ).encode("utf-8", "surrogateescape") + b"\n"
    return b"".join([
        header,
        np.ascontiguousarray(b.linenos, dtype="<i8").tobytes(),
        np.ascontiguousarray(b.offsets, dtype="<i8").tobytes(),
        b.slab,
        b"\n",
    ])


def _batch_from_body(meta: dict, body, offset: int = 0) -> LineBatch:
    """Decode one block's binary body (linenos + offsets + slab) from
    ``body`` starting at ``offset`` — the ONE place that knows the
    section layout.  ``body`` may be the whole enclosing buffer (the
    in-buffer decoder passes the intermediate file's bytes + offset, so
    no extra copy of the block is made) or an exact body slice (the
    streaming decoder)."""
    n, slab_len = int(meta["n"]), int(meta["slab"])
    linenos = np.frombuffer(body, dtype="<i8", count=n, offset=offset).astype(
        np.int64
    )
    offsets = np.frombuffer(
        body, dtype="<i8", count=n + 1, offset=offset + n * 8
    ).astype(np.int64)
    slab_at = offset + (2 * n + 1) * 8
    slab = bytes(body[slab_at : slab_at + slab_len])
    return LineBatch(
        filename=meta["file"], linenos=linenos, offsets=offsets, slab=slab
    )


def _block_body_len(meta: dict) -> int:
    n = int(meta["n"])
    return n * 8 + (n + 1) * 8 + int(meta["slab"])


def iter_blocks(path):
    """Stream records from a spill-run file (the shuffle wire format):
    KeyValue per JSONL line, LineBatch per block — without reading the
    whole file (the merge phase holds one block per run, not one run)."""
    with open(path, "rb") as f:
        while True:
            line = f.readline()
            if not line:
                return
            if line.startswith(MARKER):
                meta = json.loads(
                    line[len(MARKER) :].decode("utf-8", "surrogateescape")
                )
                body = f.read(_block_body_len(meta) + 1)  # + trailing '\n'
                yield _batch_from_body(meta, body)
            elif line.strip():
                k, v = json.loads(
                    line.decode("utf-8", "surrogateescape")
                )
                yield KeyValue(k, v)


class IdentityCollator:
    """Reduce-side collation for identity-reduce applications (the grep
    apps: ``reduce_fn = values[0]`` and keys are unique by construction,
    one per (file, line) — declared via the module attribute
    ``reduce_is_identity``).

    Orders everything by (file, line number) — the CLI's display order —
    so the job's mr-out files need NO downstream re-sort: collation
    becomes a streamed k-way merge (runtime/job.iter_results_sorted),
    closing the round-4 'collation resort' finding (the reference sorts
    once, worker.go:161-169).

    Batches stay columnar end to end; memory is bounded by spilling
    sorted runs in the shuffle wire format.  Contract: batches of one
    file arrive with internally ascending, pairwise disjoint line-number
    ranges (true for the grep apps — one map task per file, one batch per
    chunk), so batch-granularity merge keys of (file, first line) give a
    globally record-sorted stream."""

    def __init__(self, memory_limit_bytes: int = 128 << 20,
                 spill_dir: str | None = None):
        self.memory_limit = memory_limit_bytes
        self._spill_parent = spill_dir
        self._tmp: str | None = None
        self._mem: list = []
        self._mem_bytes = 0
        self._runs: list = []
        # the shared grep-key shape (runtime/job.GREP_KEY_RE duplicated
        # here only in spirit — imported lazily to keep this module a leaf)
        from distributed_grep_tpu.runtime.job import GREP_KEY_RE

        self._key_re = GREP_KEY_RE

    @property
    def spill_count(self) -> int:
        return len(self._runs)

    def _sort_key(self, item) -> tuple[str, int, int]:
        if isinstance(item, LineBatch):
            return (item.filename, int(item.linenos[0]) if len(item) else 0, 0)
        m = self._key_re.match(item.key)
        if m:
            return (m.group(1), int(m.group(2)), 1)
        return (item.key, 0, 1)

    def add_many(self, records) -> None:
        for rec in records:
            self._mem.append(rec)
            self._mem_bytes += (
                rec.nbytes + 256 if isinstance(rec, LineBatch)
                else len(rec.key) + len(rec.value) + 120
            )
            if self._mem_bytes >= self.memory_limit:
                self._spill()

    def _spill(self) -> None:
        import tempfile
        from pathlib import Path

        from distributed_grep_tpu.runtime import shuffle

        if not self._mem:
            return
        if self._tmp is None:
            self._tmp = tempfile.mkdtemp(
                prefix="dgrep-collate-", dir=self._spill_parent
            )
        run = Path(self._tmp) / f"run-{len(self._runs)}"
        self._mem.sort(key=self._sort_key)
        with open(run, "wb") as f:
            for i in range(0, len(self._mem), 1024):
                f.write(shuffle.encode_records(self._mem[i : i + 1024]))
        self._runs.append(run)
        self._mem = []
        self._mem_bytes = 0

    def merged(self):
        """All items (LineBatch | KeyValue) in (file, line) order."""
        import heapq

        self._mem.sort(key=self._sort_key)
        streams = [iter_blocks(run) for run in self._runs]
        streams.append(iter(self._mem))
        return heapq.merge(*streams, key=self._sort_key)

    def iter_output_blocks(self):
        """The mr-out content, streamed in display order as WRITER-READY
        pieces: bytes per batch (native one-pass formatter,
        ``LineBatch.format_lines_bytes``) and str per loose KeyValue —
        the reduce writer encodes str pieces utf-8/surrogateescape, so
        both land identical bytes."""
        for item in self.merged():
            if isinstance(item, LineBatch):
                if len(item):
                    yield item.format_lines_bytes()
            else:
                yield f"{item.key}\t{item.value}\n"

    def iter_output_chunks(self):
        """The mr-out text, streamed in display order: one string per
        batch (batched formatting) or per loose KeyValue."""
        for block in self.iter_output_blocks():
            yield (
                block.decode("utf-8", "surrogateescape")
                if isinstance(block, bytes) else block
            )

    def close(self) -> None:
        import shutil

        if self._tmp is not None:
            shutil.rmtree(self._tmp, ignore_errors=True)
            self._tmp = None
        self._mem = []
        self._runs = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def decode_batch_at(data: bytes, pos: int) -> tuple[LineBatch, int]:
    """Decode one batch block starting at ``pos`` (which must point at
    MARKER); returns (batch, next position)."""
    eol = data.index(b"\n", pos)
    meta = json.loads(
        data[pos + len(MARKER) : eol].decode("utf-8", "surrogateescape")
    )
    p = eol + 1
    batch = _batch_from_body(meta, data, offset=p)  # no body copy
    p += _block_body_len(meta)
    if p < len(data) and data[p : p + 1] == b"\n":
        p += 1
    return batch, p

"""Pattern automata — the "model families" of a grep framework.

A pattern compiles to one of three automaton models, in order of preference:

* ``shift_and``  — bit-parallel Shift-And masks for literals and short
                   class sequences (<= 32 symbols): the fastest TPU path,
                   pure VPU integer ops, no table gathers.
* ``dfa``        — regex subset -> Thompson NFA -> subset-construction DFA
                   with byte-class compression: the general engine.
* ``aho``        — Aho-Corasick automaton for multi-literal pattern sets,
                   emitted in the same DFA table format.

Pattern-SET models beyond the automata: ``fdr`` (bucketed pair-hash
filter for large literal sets, Hyperscan's architecture on the lane-gather
primitive), ``pairset`` (exact row-partition factorization for all-1-2-byte
sets — the family FDR cannot host), and ``approx`` (agrep k-error
Shift-And rows).

All models share the *newline-reset* property: the scan state after a '\\n'
byte is a fixed state independent of prior state.  That property is what
makes the TPU scan embarrassingly lane-parallel (state at any byte depends
only on bytes since line start), with exact host-side stitching of lines
that span lane boundaries (ops/ and SURVEY.md §5 long-context analogue).
"""

from distributed_grep_tpu.models.dfa import (
    DfaTable,
    RegexError,
    TooManyStates,
    compile_dfa,
)
from distributed_grep_tpu.models.shift_and import ShiftAndModel, try_compile_shift_and
from distributed_grep_tpu.models.aho import compile_aho_corasick
from distributed_grep_tpu.models.fdr import FdrError, FdrModel, compile_fdr
from distributed_grep_tpu.models.pairset import (
    PairsetError,
    PairsetModel,
    compile_pairset,
)

__all__ = [
    "DfaTable",
    "RegexError",
    "TooManyStates",
    "compile_dfa",
    "ShiftAndModel",
    "try_compile_shift_and",
    "compile_aho_corasick",
    "FdrError",
    "FdrModel",
    "compile_fdr",
    "PairsetError",
    "PairsetModel",
    "compile_pairset",
]

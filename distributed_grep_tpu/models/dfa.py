"""Regex subset -> NFA -> DFA compiler with byte-class compression.

The reference greps with Go's regexp, one line at a time on the host
(application/grep.go:20-30).  The TPU path instead compiles the pattern
*once* on the host into a dense DFA transition table that a byte-scan kernel
executes over the whole corpus (SURVEY.md §7 step 4).  Supported syntax —
the grep -E working set:

    literals (UTF-8 as raw byte sequences), '.', escapes (\\n \\t \\r \\\\
    \\xHH \\d \\D \\w \\W \\s \\S and escaped metachars), character classes
    [a-z] / [^...], alternation '|', groups '(...)', repeats '* + ?' and
    bounded '{m} {m,n} {m,}', anchors '^' and '$', case-insensitive flag.

Semantics baked into the table (all chosen for the TPU scan):

* **Unanchored search**: the DFA recognizes Sigma*·pattern — an accepting
  state means "a match ends at this byte".
* **Newline reset**: every state's transition on '\\n' is forced to the
  line-start state.  Patterns are rejected (NewlineInPattern) if they would
  consume '\\n', so the forcing is semantics-preserving.  This gives the
  scan its lane-parallel decomposition: state at byte i depends only on
  bytes since the start of i's line.
* **Non-consuming anchors**: '^' branches are reachable only at line start
  (initial state / after the reset); '$' is a second accept set
  ``accept_at_eol`` — a match iff the *next* byte is '\\n' (scans pad a
  trailing '\\n', so end-of-input behaves as end-of-line).  Anchors are
  supported at ANY position (round 5): mid-pattern '^'/'$' become
  position-gated epsilons (ls_eps closed over only in the start state —
  every line-start position IS the start state under newline reset;
  eol_eps folds into accept_at_eol), so '(^a|b)c' is exact and 'a^b'
  compiles to a match-nothing automaton, both per GNU line semantics.
* **Byte classes**: bytes are partitioned into equivalence classes so the
  device table is [n_states, n_classes] rather than [n_states, 256].
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


class RegexError(ValueError):
    """Malformed pattern."""


class TooManyStates(RegexError):
    """DFA exceeded the state cap — caller should fall back to the CPU engine."""


class NewlineInPattern(RegexError):
    """Pattern would consume '\\n'; the newline-reset table cannot express it."""


NL = 0x0A
_ALL = (1 << 256) - 1
_ANY_NO_NL = _ALL & ~(1 << NL)  # '.' — any byte except newline


def _mask_of(byte: int) -> int:
    return 1 << byte


def _class_mask(chars: str) -> int:
    m = 0
    for c in chars:
        m |= 1 << ord(c)
    return m


_DIGIT = _class_mask("0123456789")
_WORD = _DIGIT | _class_mask("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_")
# \s normally includes '\n', but the scan is strictly per-line (lines never
# contain '\n'), so excluding it here is semantics-preserving — and keeps \s
# usable under the newline-reset table.
_SPACE = _class_mask(" \t\r\x0b\x0c")


def _range_mask(lo: int, hi: int) -> int:
    m = 0
    for b in range(lo, hi + 1):
        m |= 1 << b
    return m


_UPPER = _range_mask(ord("A"), ord("Z"))
_LOWER = _range_mask(ord("a"), ord("z"))
_ALPHA = _UPPER | _LOWER
# POSIX bracket classes ([[:digit:]] etc.) in the C locale — GNU grep -E
# supports these and Python re does NOT, so they must compile into the
# automaton subset (there is no re fallback that could host them).  ASCII
# byte definitions; space/cntrl exclude '\n' (never matchable within a
# line — the same semantics-preserving exclusion as '.'/\s above).
_POSIX_CLASSES = {
    "alpha": _ALPHA,
    "digit": _DIGIT,
    "alnum": _ALPHA | _DIGIT,
    "upper": _UPPER,
    "lower": _LOWER,
    "space": _SPACE,
    "blank": _class_mask(" \t"),
    "punct": (_range_mask(33, 47) | _range_mask(58, 64)
              | _range_mask(91, 96) | _range_mask(123, 126)),
    "print": _range_mask(32, 126),
    "graph": _range_mask(33, 126),
    "cntrl": (_range_mask(0, 31) | _mask_of(127)) & ~_mask_of(NL),
    "xdigit": _DIGIT | _range_mask(ord("A"), ord("F"))
              | _range_mask(ord("a"), ord("f")),
}


def _mask_to_class_text(mask: int) -> bytes:
    """Class-body text (\\xHH / \\xHH-\\xHH runs) denoting `mask` — valid
    inside a bracket expression for BOTH this module's parser and
    Python re."""
    parts = []
    b = 0
    while b < 256:
        if mask >> b & 1:
            lo = b
            while b < 256 and mask >> b & 1:
                b += 1
            hi = b - 1
            parts.append(b"\\x%02x" % lo if lo == hi
                         else b"\\x%02x-\\x%02x" % (lo, hi))
        else:
            b += 1
    return b"".join(parts)


_POSIX_EXPANSIONS = {k: _mask_to_class_text(v) for k, v in _POSIX_CLASSES.items()}


def _scan_collating(src: bytes, i: int) -> tuple[int, int]:
    """``src[i:i+2]`` is ``[.`` or ``[=`` inside a bracket expression:
    a POSIX collating symbol / equivalence class.  In the C locale only
    the trivial single-character forms exist — ``[.c.]`` / ``[=c=]``
    denote the character itself; anything longer (or empty) is GNU's
    "Invalid collation character", exit 2 (GNU-verified).  Returns
    (byte, index past the closing ``.]``/``=]``)."""
    d = src[i + 1]  # ord('.') or ord('=')
    end = src.find(bytes([d, ord("]")]), i + 2)
    if end < 0:
        raise RegexError(f"unterminated '[{chr(d)}' at {i}")
    if end != i + 3:  # exactly one character between the delimiters
        raise RegexError("invalid collation character")
    return src[i + 2], end + 2


def _scan_posix_class(src: bytes, i: int) -> tuple[str, int]:
    """``src[i:i+2] == b'[:'`` inside a bracket expression: scan the
    class name.  Returns (name, index just past ':]').  Raises on an
    unterminated '[:' or an unknown name — GNU rejects both with exit 2
    ("Unmatched [ ..." / "Unknown character class name").  The ONE
    scanner shared by the parser and expand_posix_classes, so the
    validator and the automaton cannot drift."""
    end = src.find(b":]", i + 2)
    if end < 0:
        raise RegexError(f"unterminated '[:' at {i}")
    name = src[i + 2:end].decode("ascii", "replace")
    if name not in _POSIX_CLASSES:
        raise RegexError(f"unknown POSIX class [:{name}:]")
    return name, end + 2


def _reject_single_bracket_class(src: bytes, open_pos: int) -> None:
    """GNU errors on the `[:name:]` single-bracket form ("character
    class syntax is [[:space:]], not [:space:]"): a bracket expression
    whose content starts with ':' AND whose closing ']' is preceded by
    ':'.  `[:a]` (no ':]' close) stays a literal member class, like GNU,
    and the negated form `[^:name:]` rejects exactly like the plain one
    (GNU-verified).  ``open_pos`` indexes the '['."""
    j = open_pos + 1
    if j < len(src) and src[j] == ord("^"):
        j += 1
    if j >= len(src) or src[j] != ord(":"):
        return
    close = src.find(b"]", j + 1)
    if close > j + 1 and src[close - 1] == ord(":"):
        raise RegexError(
            "character class syntax is [[:name:]], not [:name:]"
        )


def expand_posix_classes(pattern):
    """Rewrite POSIX bracket classes ([[:digit:]] etc.) into \\xHH-range
    form understood by BOTH this module's parser and Python re.

    This is the single translation point for every code path that hands
    the user's pattern to re for SEMANTICS — the -w/-x confirm regexes,
    the CLI's -o matcher, apps/grep.py's reference-mirror matcher, the
    engine's re fallback: Python re has no POSIX classes and silently
    misparses ``[[:digit:]]`` as the character set {[ : d i g t}, so any
    unexpanded handoff would diverge from GNU.  Outside bracket
    expressions ``[:name:]`` has no special meaning and is left alone;
    a well-formed ``[:name:]`` with an unknown name raises RegexError
    (GNU errors on those too).  Accepts str or bytes and returns the
    same type."""
    is_str = isinstance(pattern, str)
    src = pattern.encode("utf-8", "surrogateescape") if is_str else bytes(pattern)
    out = bytearray()
    i, n = 0, len(src)
    in_class = False
    # previous in-class token kind — "none" (just opened / after ^ or a
    # leading ]), "member" (char, escaped pair, class, collating symbol),
    # "dash" (a '-' that follows a member, i.e. a potential range
    # operator).  Tracked so the range-adjacency guards can't be fooled
    # by escaped bytes the way raw last-byte peeking was (round-5
    # review: '[a\\-[:digit:]]' vs '[\\^-[:digit:]]').
    prev = "none"
    while i < n:
        c = src[i]
        if c == 0x5C and i + 1 < n:  # backslash escape, either context
            out += src[i:i + 2]
            i += 2
            if in_class:
                prev = "member"
            continue
        if not in_class:
            if c == ord("["):
                _reject_single_bracket_class(src, i)  # [:name:] like GNU
            out.append(c)
            i += 1
            if c == ord("["):
                in_class = True
                prev = "none"
                # leading '^' and a first ']' are literal class members
                if i < n and src[i] == ord("^"):
                    out.append(src[i])
                    i += 1
                if i < n and src[i] == ord("]"):
                    out.append(src[i])
                    i += 1
                    prev = "member"
            continue
        if c == ord("[") and i + 1 < n and src[i + 1] in (
            ord(":"), ord("."), ord("=")
        ):
            # dash just before: [a-[:digit:]] is GNU "Invalid range end"
            # (a LEADING '-' as in [-[:digit:]] stays a literal member)
            if prev == "dash" and src[i + 1] == ord(":"):
                raise RegexError("invalid range: POSIX class as range end")
            if src[i + 1] == ord(":"):
                name, i = _scan_posix_class(src, i)
                out += _POSIX_EXPANSIONS[name]
                # dash just after: [[:digit:]-z] is GNU "Invalid range
                # end" ([[:digit:]-] with the literal dash stays fine)
                if (i + 1 < n and src[i] == ord("-")
                        and src[i + 1] != ord("]")):
                    raise RegexError(
                        "invalid range: POSIX class as range start"
                    )
            else:
                # [.c.] / [=c=]: the character itself (C locale);
                # emit \xHH so re can't misread metacharacters
                byte, i = _scan_collating(src, i)
                out += b"\\x%02x" % byte
            prev = "member"
            continue
        if c == ord("]"):
            in_class = False
        elif c == ord("-"):
            prev = "dash" if prev == "member" else "member"
        else:
            prev = "member"
        out.append(c)
        i += 1
    res = bytes(out)
    return res.decode("utf-8", "surrogateescape") if is_str else res


# --------------------------------------------------------------------- AST

@dataclass
class Char:
    mask: int  # 256-bit membership bitmask


@dataclass
class Concat:
    parts: list


@dataclass
class Alt:
    options: list


@dataclass
class Repeat:
    node: object
    min: int
    max: int | None  # None = unbounded


@dataclass
class Anchor:
    kind: str  # "^" or "$"


_REPEAT_EXPANSION_CAP = 512  # total copies a bounded repeat may expand to


# Literal-set extraction: how many concrete byte strings an alternation /
# class product may expand to before we stop treating it as a literal set.
LITERAL_SET_CAP = 256


def enumerate_literal_set(
    pattern: str, cap: int = LITERAL_SET_CAP, *, ignore_case: bool = False
) -> list[bytes] | None:
    """The byte strings matched by ``pattern`` when it denotes a finite
    literal set — an alternation / concatenation / small-class product with
    no repeats or anchors — or None when it doesn't (or would expand past
    ``cap``).

    This is the Hyperscan-style literal decomposition: patterns like
    ``(volcano|anarchism|needle)`` are exactly literal sets, and the
    engine's pattern-set path (Aho-Corasick banks + the FDR device filter)
    scans them faster than the Glushkov NFA kernel compiled from the same
    regex.  Parsing is always case-SENSITIVE: for a case-insensitive grep
    the caller must forward ignore_case to the downstream set engine (the
    engines fold natively; enumerating folded masks here would blow the
    cap at 2^len) — but it must ALSO pass ``ignore_case`` here so negated
    classes fold their members before complementing (otherwise the
    enumeration of ``[^x]`` contains ``X``, which the set engine folds
    back to the excluded ``x``).  Newline-containing expansions return
    None (a literal with '\n' can never match within a line; the regex
    paths own that semantics)."""
    try:
        ast = _Parser(pattern, ignore_case=False,
                      fold_negated_classes=ignore_case).parse()
    except RegexError:
        return None

    def enum(node) -> list[bytes] | None:
        if isinstance(node, Char):
            byts = [b for b in range(256) if node.mask >> b & 1]
            if not byts or len(byts) > cap or NL in byts:
                return None
            return [bytes([b]) for b in byts]
        if isinstance(node, Concat):
            acc = [b""]
            for part in node.parts:
                sub = enum(part)
                if sub is None or len(acc) * len(sub) > cap:
                    return None
                acc = [a + x for a in acc for x in sub]
            return acc
        if isinstance(node, Alt):
            out: list[bytes] = []
            for opt in node.options:
                sub = enum(opt)
                if sub is None or len(out) + len(sub) > cap:
                    return None
                out.extend(sub)
            return out
        return None  # Repeat / Anchor / anything unbounded

    lits = enum(ast)
    if lits is None or not lits or any(not x for x in lits):
        return None  # empty-string members: the regex engines own those
    # dedup, preserving first-seen order (cosmetic; set engines dedup too)
    seen: set[bytes] = set()
    out = []
    for x in lits:
        if x not in seen:
            seen.add(x)
            out.append(x)
    return out


def _fold_mask(mask: int) -> int:
    """Case-close a 256-bit byte-class mask (ASCII letters only)."""
    folded = mask
    for lo, up in zip(range(ord("a"), ord("z") + 1), range(ord("A"), ord("Z") + 1)):
        if mask >> lo & 1:
            folded |= 1 << up
        if mask >> up & 1:
            folded |= 1 << lo
    return folded


class _Parser:
    """Recursive-descent parser for the grep -E subset."""

    def __init__(self, pattern: str, ignore_case: bool,
                 fold_negated_classes: bool = False):
        self.src = (pattern.encode("utf-8", "surrogateescape")
                    if isinstance(pattern, str) else bytes(pattern))
        self.pos = 0
        self.ignore_case = ignore_case
        # enumerate_literal_set parses case-SENSITIVELY (the set engines
        # fold members natively, and pre-folded masks would blow the
        # enumeration cap) — but a NEGATED class must still fold its
        # members before complementing, or the downstream per-member fold
        # re-adds the excluded letter via its case partner ([^x] -i
        # enumerates 'X', which the set engine folds back to 'x').
        self.fold_negated_classes = fold_negated_classes

    def parse(self):
        node = self._alt()
        if self.pos != len(self.src):
            raise RegexError(f"unexpected {chr(self.src[self.pos])!r} at {self.pos}")
        return node

    # alt := concat ('|' concat)*
    def _alt(self):
        options = [self._concat()]
        while self._peek() == ord("|"):
            self.pos += 1
            options.append(self._concat())
        return options[0] if len(options) == 1 else Alt(options)

    # concat := repeat*
    def _concat(self):
        parts = []
        while True:
            c = self._peek()
            if c is None or c in (ord("|"), ord(")")):
                break
            parts.append(self._repeat())
        if not parts:
            return Concat([])
        return parts[0] if len(parts) == 1 else Concat(parts)

    # repeat := atom ('*'|'+'|'?'|'{m,n}')?
    def _repeat(self):
        atom = self._atom()
        c = self._peek()
        if c == ord("*"):
            self.pos += 1
            node = Repeat(atom, 0, None)
        elif c == ord("+"):
            self.pos += 1
            node = Repeat(atom, 1, None)
        elif c == ord("?"):
            self.pos += 1
            node = Repeat(atom, 0, 1)
        elif c == ord("{"):
            node = Repeat(atom, *self._bounds())
        else:
            return atom
        if isinstance(atom, Anchor):
            raise RegexError("cannot repeat an anchor")
        if self._peek() == ord("?"):  # lazy marker — match-detection is identical
            self.pos += 1
        return node

    def _bounds(self) -> tuple[int, int | None]:
        start = self.pos
        assert self.src[self.pos] == ord("{")
        self.pos += 1
        end = self.src.find(b"}", self.pos)
        if end < 0:
            raise RegexError(f"unterminated {{...}} at {start}")
        body = self.src[self.pos : end].decode("ascii", "replace")
        self.pos = end + 1
        try:
            if "," not in body:
                m = int(body)
                return m, m
            lo, hi = body.split(",", 1)
            m = int(lo) if lo else 0
            n = int(hi) if hi else None
        except ValueError as e:
            raise RegexError(f"bad repeat bounds {{{body}}}") from e
        if n is not None and n < m:
            raise RegexError(f"bad repeat bounds {{{body}}}: max < min")
        return m, n

    def _atom(self):
        c = self._peek()
        if c is None:
            raise RegexError("unexpected end of pattern")
        if c == ord("("):
            self.pos += 1
            if self.src[self.pos : self.pos + 2] == b"?:":  # non-capturing group
                self.pos += 2
            node = self._alt()
            if self._peek() != ord(")"):
                raise RegexError(f"unbalanced '(' at {self.pos}")
            self.pos += 1
            return node
        if c == ord("["):
            return Char(self._char_class())
        if c == ord("."):
            self.pos += 1
            return Char(_ANY_NO_NL)
        if c == ord("^"):
            self.pos += 1
            return Anchor("^")
        if c == ord("$"):
            self.pos += 1
            return Anchor("$")
        if c == ord("\\"):
            nxt = self.src[self.pos + 1] if self.pos + 1 < len(self.src) else None
            if nxt in (ord("A"), ord("Z")):
                # Per-line semantics make these exact synonyms of the
                # line anchors: a line-string contains no '\n', so \A is
                # start-of-line and \Z is end-of-line (verified
                # equivalent under the per-line re oracle).  GNU grep -E
                # has no \A/\Z, so CLI parity is unaffected; library
                # callers get them for free instead of the re fallback.
                # \z stays deferred: Python re rejects it too, so there
                # is no oracle to be compatible with.
                self.pos += 2
                return Anchor("^" if nxt == ord("A") else "$")
            if nxt in (ord("b"), ord("B")):
                # Word boundaries parse into Anchor nodes (round 5).  The
                # automaton subset cannot express them (the match needs a
                # byte of lookahead the scan planes don't carry — see
                # _Nfa.build), but parsing them lets the device-filter
                # path STRIP them (models/nfa._strip_anchors: a language
                # superset at the same end offsets) and re-confirm
                # candidate lines, so '\berror\b' rides the Pallas NFA
                # filter instead of the pure per-line re loop.
                self.pos += 2
                return Anchor(chr(nxt))
            return Char(self._fold(self._escape()))
        if c in (ord("*"), ord("+"), ord("?"), ord("{"), ord("}")):
            # '{' not opening a valid bound is literal, like grep
            if c == ord("{"):
                save = self.pos
                try:
                    self.pos += 0
                    self._bounds()
                    raise RegexError("repeat with nothing to repeat")
                except RegexError as e:
                    if "nothing to repeat" in str(e):
                        raise
                    self.pos = save
            else:
                raise RegexError(f"nothing to repeat before {chr(c)!r} at {self.pos}")
        self.pos += 1
        return Char(self._fold(_mask_of(c)))

    def _escape(self, in_class: bool = False) -> int:
        self.pos += 1  # consume backslash
        if self.pos >= len(self.src):
            raise RegexError("trailing backslash")
        c = self.src[self.pos]
        self.pos += 1
        simple = {
            ord("n"): _mask_of(NL),
            ord("t"): _mask_of(9),
            ord("r"): _mask_of(13),
            ord("f"): _mask_of(12),
            ord("v"): _mask_of(11),
            ord("d"): _DIGIT,
            ord("D"): _ALL & ~_DIGIT & ~_mask_of(NL),
            ord("w"): _WORD,
            ord("W"): _ALL & ~_WORD & ~_mask_of(NL),
            ord("s"): _SPACE,
            ord("S"): _ALL & ~_SPACE,
        }
        if c in simple:
            return simple[c]
        if c == ord("x"):
            hexs = self.src[self.pos : self.pos + 2]
            if len(hexs) != 2:
                raise RegexError("bad \\x escape")
            self.pos += 2
            return _mask_of(int(hexs, 16))
        if c == ord("0"):
            # \0 plus up to 2 more octal digits (re semantics, both inside
            # and outside classes): \011 is a tab, NOT NUL + "11"
            digs = "0"
            while (len(digs) < 3 and self.pos < len(self.src)
                   and ord("0") <= self.src[self.pos] <= ord("7")):
                digs += chr(self.src[self.pos])
                self.pos += 1
            return _mask_of(int(digs, 8))
        if ord("1") <= c <= ord("9"):
            if in_class:
                if c > ord("7"):
                    # re rejects [\8]/[\9] too ("bad escape")
                    raise RegexError(f"bad escape \\{chr(c)} in class")
                # inside a class, \1.. are octal escapes (re semantics):
                # consume up to 3 octal digits
                digs = chr(c)
                while (len(digs) < 3 and self.pos < len(self.src)
                       and ord("0") <= self.src[self.pos] <= ord("7")):
                    digs += chr(self.src[self.pos])
                    self.pos += 1
                val = int(digs, 8)
                if val > 0xFF:
                    raise RegexError(f"octal escape \\{digs} out of range")
                return _mask_of(val)
            # \1..\9: a backreference, which no finite automaton expresses.
            # Raising sends the engine to its host re fallback — silently
            # treating it as a literal digit would drop matches.
            raise RegexError(f"backreference \\{chr(c)} is not supported "
                             "by the automaton subset")
        if c == ord("b") and in_class:
            return _mask_of(8)  # [\b] = backspace, like re
        if c in (ord("b"), ord("B"), ord("A"), ord("Z"), ord("z"), ord("G")):
            # zero-width assertions beyond ^/$/\b: defer to re (inside a
            # class these are invalid in re too).  \b/\B never reach here
            # at atom level — _atom parses them into Anchor nodes first
            # (round 5) so the device-filter path can strip+confirm them.
            raise RegexError(f"\\{chr(c)} assertion is not supported "
                             "by the automaton subset")
        return _mask_of(c)  # escaped literal (metachars, punctuation, ...)

    def _char_class(self) -> int:
        start = self.pos
        assert self.src[self.pos] == ord("[")
        _reject_single_bracket_class(self.src, start)  # [:name:] like GNU
        self.pos += 1
        negate = False
        if self._peek() == ord("^"):
            negate = True
            self.pos += 1
        mask = 0
        first = True
        while True:
            c = self._peek()
            if c is None:
                raise RegexError(f"unterminated '[' at {start}")
            if c == ord("]") and not first:
                self.pos += 1
                break
            first = False
            if (
                c == ord("[")
                and self.pos + 1 < len(self.src)
                and self.src[self.pos + 1] in (ord("."), ord("="))
            ):
                # [.c.] / [=c=]: trivial C-locale collating forms — the
                # character itself; longer names reject (_scan_collating)
                byte, self.pos = _scan_collating(self.src, self.pos)
                m = _mask_of(byte)
                # fall through to the range logic: [[.a.]-z] is a valid
                # range in GNU (the collating symbol is its character)
            elif (
                c == ord("[")
                and self.pos + 1 < len(self.src)
                and self.src[self.pos + 1] == ord(":")
            ):
                # POSIX bracket class [:name:] (GNU grep -E supports
                # these; Python re does not, so the re fallback can't —
                # round 5).  C-locale / ASCII byte definitions; '\n' is
                # excluded from the classes that would contain it
                # (space, cntrl) — a pattern can never consume '\n'
                # under per-line semantics, so exclusion is
                # semantics-preserving (same argument as '.').
                name, after = _scan_posix_class(self.src, self.pos)
                mask |= _POSIX_CLASSES[name]
                self.pos = after
                # a class can't be a range endpoint ([[:digit:]-z] is
                # GNU's "Invalid range end", exit 2; a trailing literal
                # '-' as in [[:digit:]-] stays fine)
                if (
                    self._peek() == ord("-")
                    and self.pos + 1 < len(self.src)
                    and self.src[self.pos + 1] != ord("]")
                ):
                    raise RegexError(
                        "invalid range: POSIX class as range start"
                    )
                continue
            elif c == ord("\\"):
                m = self._escape(in_class=True)
            else:
                self.pos += 1
                m = _mask_of(c)
            # range a-z: single char followed by '-' and another single char
            if (
                m.bit_count() == 1
                and self._peek() == ord("-")
                and self.pos + 1 < len(self.src)
                and self.src[self.pos + 1] != ord("]")
            ):
                self.pos += 1
                hi_c = self._peek()
                if (
                    hi_c == ord("[")
                    and self.pos + 1 < len(self.src)
                    and self.src[self.pos + 1] == ord(":")
                ):
                    # [a-[:digit:]]: GNU "Invalid range end", exit 2
                    raise RegexError(
                        "invalid range: POSIX class as range end"
                    )
                if (
                    hi_c == ord("[")
                    and self.pos + 1 < len(self.src)
                    and self.src[self.pos + 1] in (ord("."), ord("="))
                ):
                    # [a-[.z.]]: the collating symbol is its character
                    byte, self.pos = _scan_collating(self.src, self.pos)
                    hi_m = _mask_of(byte)
                elif hi_c == ord("\\"):
                    hi_m = self._escape(in_class=True)
                else:
                    self.pos += 1
                    hi_m = _mask_of(hi_c)
                if hi_m.bit_count() != 1:
                    raise RegexError("bad class range endpoint")
                lo_b = m.bit_length() - 1
                hi_b = hi_m.bit_length() - 1
                if hi_b < lo_b:
                    raise RegexError(f"reversed class range at {start}")
                for b in range(lo_b, hi_b + 1):
                    mask |= 1 << b
            else:
                mask |= m
        # Fold BEFORE complementing: [^x] under -i must exclude both 'x'
        # and 'X' (re/grep semantics).  Folding after would re-add the
        # excluded letter — the complement contains its case partner, and
        # expanding that partner puts the letter back (every engine path
        # shares this class mask, so the old order over-matched them all).
        # The complement of a case-closed set is itself case-closed, so no
        # second fold is needed.
        mask = self._fold(mask)
        if negate:
            if self.fold_negated_classes:
                mask = _fold_mask(mask)
            mask = _ALL & ~mask & ~_mask_of(NL)  # grep: negated classes skip \n
        return mask

    def _fold(self, mask: int) -> int:
        return _fold_mask(mask) if self.ignore_case else mask

    def _peek(self) -> int | None:
        return self.src[self.pos] if self.pos < len(self.src) else None


# --------------------------------------------------------------------- NFA

@dataclass
class _NfaState:
    # char transitions: list of (mask, target); eps: list of targets.
    # ls_eps / eol_eps carry mid-pattern anchors (round 5): an ls_eps
    # edge is traversable only at a line start (offset 0 or right after
    # '\n' — exactly the newline-reset start state's closure), an
    # eol_eps edge only when the next byte is '\n' or end-of-input
    # (folded into the accept_eol plane, like top-level '$').
    chars: list = field(default_factory=list)
    eps: list = field(default_factory=list)
    ls_eps: list = field(default_factory=list)
    eol_eps: list = field(default_factory=list)


class _Nfa:
    """Thompson construction.  Fragments are (start, accept) state-id pairs."""

    def __init__(self):
        self.states: list[_NfaState] = []

    def new_state(self) -> int:
        self.states.append(_NfaState())
        return len(self.states) - 1

    def build(self, node) -> tuple[int, int]:
        if isinstance(node, Char):
            if node.mask >> NL & 1:
                raise NewlineInPattern(
                    "pattern consumes '\\n' — not representable with line semantics"
                )
            if node.mask == 0:
                raise RegexError("empty character class matches nothing")
            s, a = self.new_state(), self.new_state()
            self.states[s].chars.append((node.mask, a))
            return s, a
        if isinstance(node, Concat):
            s = a = self.new_state()
            for part in node.parts:
                ps, pa = self.build(part)
                self.states[a].eps.append(ps)
                a = pa
            return s, a
        if isinstance(node, Alt):
            s, a = self.new_state(), self.new_state()
            for opt in node.options:
                os_, oa = self.build(opt)
                self.states[s].eps.append(os_)
                self.states[oa].eps.append(a)
            return s, a
        if isinstance(node, Repeat):
            return self._build_repeat(node)
        if isinstance(node, Anchor):
            # Mid-pattern anchors (round 5 — e.g. '(^a|b)c', 'a(b$|c)'):
            # a zero-width fragment whose epsilon is position-gated.  The
            # newline-reset scan represents both exactly: every line-start
            # position maps to the start state (ls_eps edges are closed
            # over only there), and EOL validity is the accept_eol plane
            # (eol_eps edges fold into it at subset-construction time).
            # Top-level anchors never reach here (_split_anchors pops
            # them); patterns like 'a^b' simply compile to automata with
            # no matches, exactly GNU grep's per-line semantics.
            if node.kind not in ("^", "$"):
                # \b/\B: wordness of the NEXT byte is one byte of
                # lookahead the accept planes don't carry — no exact
                # table form.  Raising routes the engine to its re
                # fallback, where the device rescue strips the anchors
                # into a filter and re-confirms candidate lines.
                raise RegexError(
                    f"\\{node.kind} assertion has no exact automaton form"
                )
            s, a = self.new_state(), self.new_state()
            edges = self.states[s].ls_eps if node.kind == "^" else self.states[s].eol_eps
            edges.append(a)
            return s, a
        raise AssertionError(f"unknown node {node!r}")

    def _build_repeat(self, node: Repeat) -> tuple[int, int]:
        m, n = node.min, node.max
        if n is not None and n > _REPEAT_EXPANSION_CAP:
            raise TooManyStates(f"repeat bound {n} exceeds expansion cap")
        if m > _REPEAT_EXPANSION_CAP:
            raise TooManyStates(f"repeat bound {m} exceeds expansion cap")
        s = a = self.new_state()
        for _ in range(m):  # required copies
            ps, pa = self.build(node.node)
            self.states[a].eps.append(ps)
            a = pa
        if n is None:  # star over one more copy
            ps, pa = self.build(node.node)
            self.states[a].eps.append(ps)
            self.states[pa].eps.append(ps)
            end = self.new_state()
            self.states[a].eps.append(end)
            self.states[pa].eps.append(end)
            return s, end
        for _ in range(n - m):  # optional copies: a -> ps..pa -> end, skip a -> end
            ps, pa = self.build(node.node)
            end = self.new_state()
            self.states[a].eps.append(ps)
            self.states[a].eps.append(end)
            self.states[pa].eps.append(end)
            a = end
        return s, a


# --------------------------------------------------------------------- DFA

@dataclass
class DfaTable:
    """Dense scan tables, device- and host-ready.

    trans        [n_states, n_classes] uint16 — next state per byte class
    byte_to_cls  [256] unsigned int (uint8 from compile_dfa, uint16 from
                 aho — full-alphabet rulesets reach 256 classes)
    accept       [n_states] bool — a match ends at this byte
    accept_eol   [n_states] bool — a match ends here iff next byte is '\\n'
                 (the '$' accept set; scans pad a trailing '\\n')
    start        line-start state (also every state's target on '\\n')
    """

    trans: np.ndarray
    byte_to_cls: np.ndarray
    accept: np.ndarray
    accept_eol: np.ndarray
    start: int
    pattern: str

    @property
    def n_states(self) -> int:
        return self.trans.shape[0]

    @property
    def n_classes(self) -> int:
        return self.trans.shape[1]

    def full_table(self) -> np.ndarray:
        """[n_states, 256] uint16 — for the native/C++ scanner oracle.

        Cached: a 10k-pattern Aho-Corasick bank densifies to ~30 MB, and
        the engine's per-line confirm/stitch path calls this once per
        suspect line."""
        full = getattr(self, "_full_cache", None)
        if full is None:
            full = np.ascontiguousarray(self.trans[:, self.byte_to_cls])
            full.flags.writeable = False  # shared across calls
            object.__setattr__(self, "_full_cache", full)
        return full


@dataclass
class StrideTable:
    """k-byte-stride composition of a DfaTable for the device scan.

    The per-byte DFA recurrence costs one table gather per scanned byte; on
    TPU the gather (and the lax.scan step overhead around it) dominates.
    Composing transitions over k bytes turns the scan into chunk/k steps of
    ONE gather from a [n_states, n_classes**k] table whose int32 entries pack
    the landing state with a k-bit accept bitmap:

        entry = (state_after_k_bytes << k) | accept_bitmap
        bit t of accept_bitmap = accept[state after consuming byte t]

    The bitmap preserves exact per-byte match offsets (a match ending
    mid-stride keeps its true position, so line attribution across a '\\n'
    inside the stride stays correct), and newline-reset transitions compose
    through the table like any other byte.  '$' accepts (accept_eol) need
    next-byte context, so patterns using them keep stride 1.
    """

    trans_k: np.ndarray  # [n_states, n_classes**k] int32 packed entries
    byte_to_cls: np.ndarray  # [256] (shared with the base table)
    k: int
    n_classes: int  # base (1-byte) class count
    start: int

    @property
    def n_states(self) -> int:
        return self.trans_k.shape[0]


def choose_stride(
    table: DfaTable, max_entries: int = 1 << 23, max_cols: int = 1 << 13
) -> int:
    """Largest k in {4,2,1} whose composed table fits the budget (entries
    cap bounds HBM/upload cost; column cap bounds the combined-class index
    range).  Powers of two only: scan layouts pad chunk to a multiple of 8,
    which k must divide."""
    if table.accept_eol.any():
        return 1
    for k in (4, 2):
        cols = table.n_classes**k
        if cols <= max_cols and table.n_states * cols <= max_entries:
            return k
    return 1


def build_stride_table(table: DfaTable, k: int) -> StrideTable:
    """Compose the DFA over k-byte strides (vectorized over states)."""
    if k < 1:
        raise ValueError(f"stride must be >= 1, got {k}")
    if k > 1 and table.accept_eol.any():
        raise ValueError("'$' accepts need next-byte context; stride must be 1")
    S, C = table.n_states, table.n_classes
    trans = table.trans.astype(np.int64)  # [S, C]
    accept = table.accept

    # states[s, j] = state after consuming the byte sequence j (base-C digits,
    # most significant = first byte), starting from s.  bitmap accumulates
    # accept bits at each step.
    states = np.arange(S, dtype=np.int64)[:, None]  # [S, 1] identity column
    bitmap = np.zeros((S, 1), dtype=np.int64)
    for t in range(k):
        # extend each sequence by one byte class: [S, C**t] -> [S, C**(t+1)]
        states = trans[states]  # [S, cols, C]
        states = states.reshape(S, -1)
        bitmap = (np.repeat(bitmap[:, :, None], C, axis=2).reshape(S, -1)
                  | (accept[states].astype(np.int64) << t))
    packed = (states << k) | bitmap
    return StrideTable(
        trans_k=np.ascontiguousarray(packed.astype(np.int32)),
        byte_to_cls=table.byte_to_cls,
        k=k,
        n_classes=C,
        start=table.start,
    )


def reference_scan(table: DfaTable, data: bytes) -> np.ndarray:
    """Host-side oracle: end offsets (index+1) of every match in `data`.

    Uses the native C scanner (utils/native.py) for the plain accept set and
    handles the '$' accept set (accept_eol: match iff next byte is '\\n' or
    end-of-input) in numpy on top of the same state sequence.  Always
    returns int64 — multi-table callers concatenate results, and a mixed
    uint64/int64 concat would silently promote to float64.
    """
    from distributed_grep_tpu.utils import native

    full = table.full_table()
    if len(data) >= native.MT_THRESHOLD_BYTES:
        # multi-core native scan; newline-aligned chunks keep it exact
        offsets = native.dfa_scan_mt(
            data, full, table.accept.astype(np.uint8), table.start
        )
    else:
        offsets, _ = native.dfa_scan(
            data, full, table.accept.astype(np.uint8), table.start
        )
    offsets = offsets.astype(np.int64)
    if not table.accept_eol.any():
        return offsets
    # '$' accepts: rescan with accept_eol as the accept set (same native
    # scanner — the state sequence is identical), then keep only offsets
    # whose NEXT byte is '\n' (or end-of-input).  Replaces the round-1
    # per-byte Python walk (~5 MB/s — it made every native-mode '$' scan
    # host-bound) with a second native pass + one vectorized compare.
    n = len(data)
    eol_accept = table.accept_eol.astype(np.uint8)
    if n >= native.MT_THRESHOLD_BYTES:
        eol_offs = native.dfa_scan_mt(data, full, eol_accept, table.start)
    else:
        eol_offs, _ = native.dfa_scan(data, full, eol_accept, table.start)
    if eol_offs.size:
        e = eol_offs.astype(np.int64)
        arr = np.frombuffer(data, dtype=np.uint8)
        keep = (e == n) | (arr[np.minimum(e, n - 1)] == NL)
        if n and arr[n - 1] == NL and table.accept_eol[table.start]:
            # a trailing '\n' parks the scan in the start state at offset
            # n; a zero-width accept there would be a phantom line GNU
            # does not count (consuming matches cannot end at n — they
            # would contain the '\n').  Drop it.
            keep &= e != n
        eol_offs = e[keep]
    # the byte-walk reports accepts only AFTER consuming a byte, so a
    # zero-width accept at position 0 (empty FIRST line — '^$', '$^')
    # never surfaces from the native pass; inject offset 0, which the
    # line attribution maps to line 1 (matching re.finditer's end()==0).
    # n > 0 only: empty input has ZERO lines, so there is no line 1 for a
    # zero-width match to land on (GNU reports no match on an empty file).
    if table.accept_eol[table.start] and n > 0 and data[0] == NL:
        eol_offs = np.concatenate([[0], eol_offs.astype(np.int64)])
    if not eol_offs.size:
        return offsets
    return np.unique(
        np.concatenate([offsets, eol_offs.astype(np.int64)])
    )


def matched_lines(table: DfaTable, data: bytes) -> set[int]:
    """1-based line numbers containing at least one match — grep's contract."""
    offsets = reference_scan(table, data)
    if offsets.size == 0:
        return set()
    nl = np.flatnonzero(np.frombuffer(data, dtype=np.uint8) == NL)
    # line number of byte position p (0-based p) = count of newlines before p, +1
    return set((np.searchsorted(nl, offsets - 1, side="right") + 1).tolist())


def _split_anchors(node):
    """Pull top-level '^'/'$' anchors out of each alternation branch.

    Returns list of (anchored_start, body, anchored_end) triples.
    """
    branches = node.options if isinstance(node, Alt) else [node]
    out = []
    for b in branches:
        parts = list(b.parts) if isinstance(b, Concat) else [b]
        a_start = a_end = False
        while parts and isinstance(parts[0], Anchor) and parts[0].kind == "^":
            a_start = True
            parts.pop(0)
        while parts and isinstance(parts[-1], Anchor) and parts[-1].kind == "$":
            a_end = True
            parts.pop()
        body = Concat(parts) if len(parts) != 1 else parts[0]
        out.append((a_start, body, a_end))
    return out


def compile_dfa(
    pattern: str,
    ignore_case: bool = False,
    max_states: int = 4096,
) -> DfaTable:
    """Compile a grep -E subset pattern into newline-reset scan tables."""
    ast = _Parser(pattern, ignore_case).parse()
    branches = _split_anchors(ast)

    nfa = _Nfa()
    root = nfa.new_state()  # line-start entry: active at line starts only
    floating = nfa.new_state()  # Sigma* self-loop: unanchored search restarts
    nfa.states[root].eps.append(floating)
    nfa.states[floating].chars.append((_ANY_NO_NL, floating))

    accepts_now: set[int] = set()
    accepts_eol: set[int] = set()
    for a_start, body, a_end in branches:
        s, a = nfa.build(body)
        (nfa.states[root] if a_start else nfa.states[floating]).eps.append(s)
        (accepts_eol if a_end else accepts_now).add(a)

    # --- eps closures -----------------------------------------------------
    n = len(nfa.states)
    closures: list[frozenset[int]] = [frozenset()] * n

    def closure(seed: frozenset[int], ls: bool = False) -> frozenset[int]:
        """Epsilon closure; ``ls=True`` additionally traverses ls_eps
        edges (mid-pattern '^') — valid only for the start state, whose
        context IS "at a line start": offset 0 and every post-'\\n'
        position reset to it, and no other DFA state ever corresponds to
        a line-start position."""
        stack, seen = list(seed), set(seed)
        while stack:
            s = stack.pop()
            nxt = nfa.states[s].eps
            if ls:
                nxt = nxt + nfa.states[s].ls_eps
            for t in nxt:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    # Mid-pattern '$' (eol_eps edges): a state that can cross an eol edge
    # and then reach an accept through eps/eol edges ONLY (no byte may be
    # consumed after asserting end-of-line within a line) accepts at EOL.
    # ls_eps edges are NOT traversed here: '$^' would need the match to
    # span a newline, which per-line semantics (and GNU grep) exclude.
    all_accepts = accepts_now | accepts_eol
    eol_sources: set[int] = set()
    for sid in range(len(nfa.states)):
        targets = nfa.states[sid].eol_eps
        if not targets:
            continue
        stack, seen = list(targets), set(targets)
        while stack:
            u = stack.pop()
            for v in nfa.states[u].eps + nfa.states[u].eol_eps:
                if v not in seen:
                    seen.add(v)
                    stack.append(v)
        if seen & all_accepts:
            eol_sources.add(sid)

    # --- byte classes -----------------------------------------------------
    # Two bytes are equivalent iff they belong to exactly the same set of
    # transition masks; '\n' is always its own class (the reset column).
    masks = sorted({m for st in nfa.states for (m, _) in st.chars})
    sig_to_cls: dict[tuple, int] = {}
    byte_to_cls = np.zeros(256, dtype=np.uint8)
    cls_repr: list[int] = []
    for b in range(256):
        s = ("NL",) if b == NL else tuple((m >> b) & 1 for m in masks)
        if s not in sig_to_cls:
            sig_to_cls[s] = len(sig_to_cls)
            cls_repr.append(b)
        byte_to_cls[b] = sig_to_cls[s]
    n_classes = len(sig_to_cls)
    nl_cls = int(byte_to_cls[NL])

    # --- subset construction ---------------------------------------------
    start_set = closure(frozenset({root}), ls=True)
    dfa_index: dict[frozenset[int], int] = {start_set: 0}
    order: list[frozenset[int]] = [start_set]
    rows: list[list[int]] = []

    i = 0
    while i < len(order):
        S = order[i]
        i += 1
        row = [0] * n_classes
        for c in range(n_classes):
            if c == nl_cls:
                row[c] = 0  # newline reset: every state -> line start
                continue
            b = cls_repr[c]
            moved = set()
            for s in S:
                for mask, t in nfa.states[s].chars:
                    if mask >> b & 1:
                        moved.add(t)
            T = closure(frozenset(moved)) if moved else frozenset()
            if T not in dfa_index:
                if len(order) >= max_states:
                    raise TooManyStates(
                        f"pattern {pattern!r} needs >{max_states} DFA states"
                    )
                dfa_index[T] = len(order)
                order.append(T)
            row[c] = dfa_index[T]
        rows.append(row)

    n_states = len(order)
    trans = np.asarray(rows, dtype=np.uint16)
    accept = np.array([bool(S & accepts_now) for S in order], dtype=bool)
    accept_eol = np.array(
        [bool(S & accepts_eol) or bool(S & eol_sources) for S in order],
        dtype=bool,
    )
    # EMPTY-line case: in the start state at EOL the position is a line
    # start AND an end-of-line simultaneously, so chains mixing '$' and
    # '^' in either order ('$^', '$(^|b)') hold there — and only there
    # (no other DFA state is ever at a line start).  The eol_sources walk
    # above deliberately excludes ls_eps (mid-line '$^' must stay dead),
    # so re-walk from the start set with ALL non-consuming edge kinds.
    if not accept_eol[0]:
        stack = list(start_set)
        seen = set(stack)
        while stack:
            u = stack.pop()
            st_u = nfa.states[u]
            for v in st_u.eps + st_u.ls_eps + st_u.eol_eps:
                if v not in seen:
                    seen.add(v)
                    stack.append(v)
        # An assertion-only accepting chain from line start is exactly
        # "the empty line matches".  (If it needed no eol edge at all,
        # accept[0] is already True and every line matches — setting the
        # eol plane too is subsumed, not wrong.)
        if seen & all_accepts:
            accept_eol[0] = True
    return DfaTable(
        trans=trans,
        byte_to_cls=byte_to_cls,
        accept=accept,
        accept_eol=accept_eol,
        start=0,
        pattern=pattern if isinstance(pattern, str) else repr(pattern),
    )

"""Shift-And bit-parallel model for literals and short class sequences.

The fastest TPU scan path: the automaton state is one uint32 per lane, and a
byte step is ``s = ((s << 1) | 1) & B[byte]`` — pure VPU integer ops, no
table gathers (Pallas TPU has no vector gather; B[byte] is computed with
per-symbol compare/or, ops/shift_and_scan.py).  Bit j of ``s`` means "the
first j+1 symbols of the pattern match ending at this byte"; a match ends
where bit m-1 is set.

Eligible patterns: a plain concatenation of single-byte chars / classes
(after case folding), length <= 32, no anchors/alternation/repeats — i.e.
what a literal grep or a character-class literal like 'h[ae]llo' compiles
to.  Everything else uses the DFA model.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from distributed_grep_tpu.models import dfa as _dfa
from distributed_grep_tpu.models.dfa import NL, Char, Concat, RegexError

MAX_SYMBOLS = 32  # state fits a uint32 lane


@dataclass
class ShiftAndModel:
    """B-masks for the Shift-And scan.

    b_table    [256] uint32 — B[byte]: bit j set iff byte matches symbol j
    sym_ranges per symbol, the byte set as sorted disjoint (lo, hi) ranges —
               lets the Pallas kernel compute B[byte] with range compares
               instead of a table gather (Pallas TPU has no vector gather)
    length     number of symbols (match bit = length - 1)
    """

    b_table: np.ndarray
    sym_ranges: list[list[tuple[int, int]]]
    length: int
    pattern: str

    @property
    def match_bit(self) -> np.uint32:
        return np.uint32(1 << (self.length - 1))

    @property
    def total_ranges(self) -> int:
        return sum(len(r) for r in self.sym_ranges)


def try_compile_shift_and(
    pattern: str, ignore_case: bool = False
) -> ShiftAndModel | None:
    """Compile if the pattern is a Shift-And-eligible symbol sequence, else None."""
    try:
        ast = _dfa._Parser(pattern, ignore_case).parse()
    except RegexError:
        return None  # let compile_dfa surface the syntax error

    parts = ast.parts if isinstance(ast, Concat) else [ast]
    if not parts:
        return None
    sym_masks: list[int] = []
    for p in parts:
        if not isinstance(p, Char):
            return None  # repeats/alts/anchors -> DFA model
        if p.mask >> NL & 1:
            return None  # newline-consuming -> CPU fallback path decides
        sym_masks.append(p.mask)
    if len(sym_masks) > MAX_SYMBOLS:
        return None

    b = np.zeros(256, dtype=np.uint32)
    for j, mask in enumerate(sym_masks):
        bit = np.uint32(1 << j)
        for byte in range(256):
            if mask >> byte & 1:
                b[byte] |= bit
    return ShiftAndModel(
        b_table=b,
        sym_ranges=[_mask_to_ranges(m) for m in sym_masks],
        length=len(sym_masks),
        pattern=pattern,
    )


# ------------------------------------------------------------- SWAR packing

# The SWAR shift-and kernel (ops/pallas_scan.swar_shift_and_scan_words)
# packs FOUR stripes' automata into each u32 lane element (one byte-plane
# per stripe), so state, B-mask build, and the coarse accumulate all run
# on 4 corpus bytes per i32 lane element instead of one.  That needs the
# whole automaton — state bits AND match bit — to fit one byte, and every
# checked symbol class to be a small set of exact byte VALUES (the SWAR
# zero-byte detect tests equality; range compares have no cheap packed
# form).  Wildcard positions (the rare-class filter) cost nothing, as in
# the unpacked kernel.
SWAR_MAX_SYMBOLS = 8  # state + match bit within each stripe's byte
SWAR_MAX_VALUES = 16  # total equality tests per byte step (ALU budget)


def swar_values(model: ShiftAndModel) -> list[tuple[int, ...]] | None:
    """Per-symbol byte values for the SWAR packed kernel, or None when the
    model is ineligible (too long, non-singleton ranges, value budget).
    An empty tuple marks a wildcard position (checked nowhere)."""
    if model.length > SWAR_MAX_SYMBOLS:
        return None
    out: list[tuple[int, ...]] = []
    total = 0
    for ranges in model.sym_ranges:
        vals = []
        for lo, hi in ranges:
            if lo != hi:
                return None  # a real range: no packed equality form
            vals.append(lo)
        total += len(vals)
        out.append(tuple(vals))
    if total > SWAR_MAX_VALUES:
        return None
    return out


# ------------------------------------------------------- rare-class filter

# Byte-frequency prior for choosing which classes the device filter checks.
# English letter frequencies (upper+lower folded), whitespace/digits, and a
# uniform floor for everything else.  Exactness NEVER depends on this prior
# — it only tunes device work vs host confirm (the span-confirm pass in
# ops/engine.py restores exact lines either way), and the engine disables
# the filter for the rest of a scan if a segment's candidate rate shows
# the prior was badly wrong for the corpus.
_LETTER_FREQ = {
    "e": 0.127, "t": 0.091, "a": 0.082, "o": 0.075, "i": 0.070, "n": 0.067,
    "s": 0.063, "h": 0.061, "r": 0.060, "d": 0.043, "l": 0.040, "c": 0.028,
    "u": 0.028, "m": 0.024, "w": 0.024, "f": 0.022, "g": 0.020, "y": 0.020,
    "p": 0.019, "b": 0.015, "v": 0.0098, "k": 0.0077, "x": 0.0015,
    "q": 0.00095, "j": 0.00015, "z": 0.00007,
}


def _byte_prior() -> np.ndarray:
    prior = np.full(256, 1.0 / 256, dtype=np.float64)
    for ch, f in _LETTER_FREQ.items():
        prior[ord(ch)] = f
        prior[ord(ch.upper())] = f / 4  # uppercase much rarer in prose
    prior[ord(" ")] = 0.15
    for d in b"0123456789":
        prior[d] = 0.01
    return prior / prior.sum()


_PRIOR = _byte_prior()


def _text_prior() -> np.ndarray:
    """Prose-conditional byte prior: the `_byte_prior` weights renormalized
    over printable ASCII + whitespace only.

    `_byte_prior`'s uniform 1/256 floor over all 256 byte values divides
    its mass ~2.25x below real prose frequencies (' ' is ~15% of text
    bytes, but the normalized prior says 6.7%) — right for ranking classes
    by rarity (its original job), but an underestimate when a DENSITY gate
    needs an absolute matches-per-byte number for a text corpus
    (models/pairset.expected_match_density).  Gates take the max of the
    two priors' estimates: this one models text, the floored one models
    binary corpora.

    `_LETTER_FREQ` is conditioned on letters only (sums to ~1), so the
    weights here rescale it by the letter share of prose characters
    (~70% lowercase, ~1/15 of that uppercase) around space at ~17% —
    the standard all-character English distribution."""
    w = np.zeros(256, dtype=np.float64)
    w[9] = 0.002  # tab
    w[10] = 0.02  # newline (members never contain it; mass only)
    w[33:127] = 0.0015  # punctuation floor
    for ch, f in _LETTER_FREQ.items():
        w[ord(ch)] = f * 0.70
        w[ord(ch.upper())] = f * 0.70 / 15
    w[ord(" ")] = 0.17
    for d in b"0123456789":
        w[d] = 0.006
    return w / w.sum()

# Keep adding checked classes until the modeled false-candidate rate drops
# below this.  Economics: a span candidate costs ~1 us of host line confirm,
# the full-class device scan ~5 ps/byte — at 2e-6/byte the confirm is ~2 ps
# /byte, safely hidden, with ~2.5x margin for prior error.
FILTER_FP_TARGET = 2e-6


def filtered_for_device(
    model: ShiftAndModel, fp_target: float = FILTER_FP_TARGET
) -> ShiftAndModel | None:
    """A device-filter variant of ``model`` that checks only its rarest
    byte-classes (remaining positions become wildcards), or None when no
    class can be dropped.

    The per-class compare chain is the Pallas kernel's ALU bottleneck
    (ops/pallas_scan.py); every dropped class removes its compares while
    the kernel's span-candidate contract is preserved — candidates stay a
    superset, the engine's span line confirm restores exactness.  Classes
    are added rarest-first (every position of a chosen class is checked:
    repeated classes square their frequency for free) until the modeled
    false-candidate rate on the byte prior clears ``fp_target``."""
    classes: dict[tuple, list[int]] = {}
    for j, ranges in enumerate(model.sym_ranges):
        classes.setdefault(tuple(ranges), []).append(j)

    def freq(ranges: tuple) -> float:
        return float(sum(_PRIOR[lo : hi + 1].sum() for lo, hi in ranges))

    order = sorted(classes.items(), key=lambda kv: freq(kv[0]))
    fp = 1.0
    kept: set[int] = set()
    for ranges, positions in order:
        kept.update(positions)
        fp *= freq(ranges) ** len(positions)
        if fp <= fp_target:
            break
    if len(kept) == model.length:
        return None  # nothing dropped — use the full model
    b = model.b_table.copy()
    sym_ranges: list[list[tuple[int, int]]] = []
    for j in range(model.length):
        if j in kept:
            sym_ranges.append(model.sym_ranges[j])
        else:
            sym_ranges.append([])  # wildcard: every byte matches position j
            b |= np.uint32(1 << j)
    return ShiftAndModel(
        b_table=b, sym_ranges=sym_ranges, length=model.length,
        pattern=model.pattern,
    )


def _mask_to_ranges(mask: int) -> list[tuple[int, int]]:
    """256-bit membership mask -> sorted disjoint inclusive (lo, hi) ranges."""
    ranges: list[tuple[int, int]] = []
    b = 0
    while b < 256:
        if mask >> b & 1:
            lo = b
            while b < 256 and mask >> b & 1:
                b += 1
            ranges.append((lo, b - 1))
        else:
            b += 1
    return ranges


def scan_reference(model: ShiftAndModel, data: bytes) -> np.ndarray:
    """Host-side oracle: end offsets (index+1) of every match."""
    s = np.uint32(0)
    hits = []
    b = model.b_table
    mb = model.match_bit
    for i, byte in enumerate(data):
        s = np.uint32(((np.uint32(s) << np.uint32(1)) | np.uint32(1)) & b[byte])
        if s & mb:
            hits.append(i + 1)
    return np.asarray(hits, dtype=np.uint64)

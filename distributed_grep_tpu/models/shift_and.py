"""Shift-And bit-parallel model for literals and short class sequences.

The fastest TPU scan path: the automaton state is one uint32 per lane, and a
byte step is ``s = ((s << 1) | 1) & B[byte]`` — pure VPU integer ops, no
table gathers (Pallas TPU has no vector gather; B[byte] is computed with
per-symbol compare/or, ops/shift_and_scan.py).  Bit j of ``s`` means "the
first j+1 symbols of the pattern match ending at this byte"; a match ends
where bit m-1 is set.

Eligible patterns: a plain concatenation of single-byte chars / classes
(after case folding), length <= 32, no anchors/alternation/repeats — i.e.
what a literal grep or a character-class literal like 'h[ae]llo' compiles
to.  Everything else uses the DFA model.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from distributed_grep_tpu.models import dfa as _dfa
from distributed_grep_tpu.models.dfa import NL, Char, Concat, RegexError

MAX_SYMBOLS = 32  # state fits a uint32 lane


@dataclass
class ShiftAndModel:
    """B-masks for the Shift-And scan.

    b_table    [256] uint32 — B[byte]: bit j set iff byte matches symbol j
    sym_ranges per symbol, the byte set as sorted disjoint (lo, hi) ranges —
               lets the Pallas kernel compute B[byte] with range compares
               instead of a table gather (Pallas TPU has no vector gather)
    length     number of symbols (match bit = length - 1)
    """

    b_table: np.ndarray
    sym_ranges: list[list[tuple[int, int]]]
    length: int
    pattern: str

    @property
    def match_bit(self) -> np.uint32:
        return np.uint32(1 << (self.length - 1))

    @property
    def total_ranges(self) -> int:
        return sum(len(r) for r in self.sym_ranges)


def try_compile_shift_and(
    pattern: str, ignore_case: bool = False
) -> ShiftAndModel | None:
    """Compile if the pattern is a Shift-And-eligible symbol sequence, else None."""
    try:
        ast = _dfa._Parser(pattern, ignore_case).parse()
    except RegexError:
        return None  # let compile_dfa surface the syntax error

    parts = ast.parts if isinstance(ast, Concat) else [ast]
    if not parts:
        return None
    sym_masks: list[int] = []
    for p in parts:
        if not isinstance(p, Char):
            return None  # repeats/alts/anchors -> DFA model
        if p.mask >> NL & 1:
            return None  # newline-consuming -> CPU fallback path decides
        sym_masks.append(p.mask)
    if len(sym_masks) > MAX_SYMBOLS:
        return None

    b = np.zeros(256, dtype=np.uint32)
    for j, mask in enumerate(sym_masks):
        bit = np.uint32(1 << j)
        for byte in range(256):
            if mask >> byte & 1:
                b[byte] |= bit
    return ShiftAndModel(
        b_table=b,
        sym_ranges=[_mask_to_ranges(m) for m in sym_masks],
        length=len(sym_masks),
        pattern=pattern,
    )


def _mask_to_ranges(mask: int) -> list[tuple[int, int]]:
    """256-bit membership mask -> sorted disjoint inclusive (lo, hi) ranges."""
    ranges: list[tuple[int, int]] = []
    b = 0
    while b < 256:
        if mask >> b & 1:
            lo = b
            while b < 256 and mask >> b & 1:
                b += 1
            ranges.append((lo, b - 1))
        else:
            b += 1
    return ranges


def scan_reference(model: ShiftAndModel, data: bytes) -> np.ndarray:
    """Host-side oracle: end offsets (index+1) of every match."""
    s = np.uint32(0)
    hits = []
    b = model.b_table
    mb = model.match_bit
    for i, byte in enumerate(data):
        s = np.uint32(((np.uint32(s) << np.uint32(1)) | np.uint32(1)) & b[byte])
        if s & mb:
            hits.append(i + 1)
    return np.asarray(hits, dtype=np.uint64)

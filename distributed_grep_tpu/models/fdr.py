"""FDR-style bucketed literal-set filter model (Hyperscan's large-set idea,
re-derived for the TPU VPU's lane-gather primitive).

Large literal sets (BASELINE.json configs 3 and 5 — grep -f / Snort-style
rulesets) are the one workload where the reference's per-line regex loop
(/root/reference/application/grep.go:20-30) has no small automaton: an
Aho-Corasick DFA over 10k patterns has ~60k states, and a per-byte table
gather at that size is the XLA scan path's ~0.1 GB/s cliff.  Hyperscan's
answer is FDR: superimpose the set into a few *buckets*, filter the stream
with shift-AND over per-position reach tables, and confirm rare candidates
exactly.  This module is that idea rebuilt around what the TPU can do fast:

* 32 buckets — one uint32 per lane, the same tile shape every other kernel
  here uses;
* reach tables indexed by a *pair-domain hash* ``h = ((b0*37) ^ (b1*101))
  & (D-1)`` of two consecutive bytes — single-byte reach saturates at these
  set sizes, a pair domain of 128..512 entries keeps per-bucket densities
  in the few-percent range;
* D <= 512 because the kernel's lane-gather (``take_along_axis`` over a
  128-lane vreg) covers 128 entries per op — D/128 gathers + selects per
  lookup (ops/pallas_fdr.py);
* the filter checks the last ``m+1`` bytes of every position (m pair
  checks, m <= 5); a candidate only says "some bucket's superimposition
  matched here" — the engine re-checks the candidate's *line* on the host
  with the exact Aho-Corasick tables (ops/engine.py), so end-to-end output
  is exact, mirroring how boundary lines are already stitched.
* sets whose densities are still too high shard into independent *banks*
  (extra device passes over the same bytes), length-stratified so short
  patterns don't drag the window down for everyone.

The expected false-positive rate is computed exactly from the built tables
(``FdrBank.fp_per_byte``), and bank/domain choice is a small cost search
over that estimate — not a heuristic guess.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

NL = 0x0A
N_BUCKETS = 32
MAX_M = 5  # pair checks per position; window = MAX_M + 1 bytes
DOMAINS = (128, 256, 512)  # kernel gathers per lookup = D / 128
# Two independent pair hashes: ANDing both lookups squares the per-check
# density (d -> d1*d2), which beats adding banks for dense full-alphabet
# sets (a 10k Snort-style set needs 12 single-hash banks but only 2
# two-hash banks for the same FP) at 2x the per-bank lookup cost.
HASHES = ((37, 101), (171, 59))
# Sets whose best achievable candidate rate is still above this are not
# worth filtering (the host confirm would dominate): compile_fdr raises and
# the engine keeps the exact DFA banks instead.
FP_CEILING_PER_BYTE = 1e-2
# Mosaic compile ceiling, measured on TPU v5e (2026-07-30): kernels up to 24
# lane-gathers per byte compile; 32 (e.g. m=4 x D=512 x 2 hashes) crash the
# compiler.  The tuner never emits a bank over this.
MAX_GATHERS = 24
# Total-cost model for the tuner, per scanned byte: one scan_cost unit
# costs ~2.1 ps on v5e (calibrated: a 480-unit 12-bank config measured
# 1.0 GB/s), and one expected candidate costs ~120 ns of host confirm
# (~120-byte line re-scanned by the native DFA at ~1 GB/s).  The optimum
# trades filter passes against confirm work instead of chasing a fixed FP.
COST_PS_PER_UNIT = 2.1
CONFIRM_PS_PER_CANDIDATE = 120_000.0


def pair_hash(b0: np.ndarray | int, b1: np.ndarray | int, domain: int, which: int = 0):
    """The kernel's pair-domain hash — shared host/device definition."""
    a, b = HASHES[which]
    return ((b0 * a) ^ (b1 * b)) & (domain - 1)


class FdrError(ValueError):
    pass


@dataclass(frozen=True)
class FdrBank:
    """One filter pass: m pair-position reach tables over a D-entry domain,
    optionally ANDed across two independent hashes."""

    m: int  # pair checks (window = m+1 bytes)
    domain: int  # table entries; D/128 lane-gathers per lookup
    tables: np.ndarray  # (n_hashes, m, domain) uint32 bucket masks
    patterns: list[bytes]  # normalized members (for debugging/repr)
    fp_per_byte: float  # expected candidate rate on uniform bytes

    @property
    def n_hashes(self) -> int:
        return self.tables.shape[0]

    @property
    def n_subtables(self) -> int:
        return self.domain // 128

    def scan_cost(self) -> int:
        """Relative per-byte device cost (gathers dominate)."""
        return self.m * self.n_hashes * (2 * self.n_subtables + 2)


@dataclass(frozen=True)
class FdrModel:
    banks: list[FdrBank]
    ignore_case: bool
    n_patterns: int

    @property
    def fp_per_byte(self) -> float:
        return float(sum(b.fp_per_byte for b in self.banks))

    def scan_cost(self) -> int:
        return sum(b.scan_cost() for b in self.banks)

    @property
    def window(self) -> int:
        """Max filter window — candidate misses are confined to the first
        window-1 bytes of a stripe (the engine's boundary stitching)."""
        return max(b.m for b in self.banks) + 1


def _normalize(patterns: list[str | bytes], ignore_case: bool) -> list[bytes]:
    out: list[bytes] = []
    for p in patterns:
        b = p.encode("utf-8", "surrogateescape") if isinstance(p, str) else bytes(p)
        if not b:
            raise FdrError("empty literal in pattern set")
        if NL in b:
            raise FdrError("literal contains '\\n' — not representable per-line")
        out.append(b.lower() if ignore_case else b)
    return out


def _bank_tables(group: list[bytes], m: int, domain: int, n_hashes: int) -> np.ndarray:
    """Build (n_hashes, m, domain) uint32 reach tables for one bank.

    Bucket assignment sorts patterns by their final-pair hash so literals
    sharing a tail land in the same bucket — distinct hashes per (bucket,
    position) is what sets the density, so clustering identical tails is
    free selectivity.
    """
    order = sorted(
        range(len(group)),
        key=lambda i: int(pair_hash(group[i][-2], group[i][-1], domain)),
    )
    tables = np.zeros((n_hashes, m, domain), dtype=np.uint32)
    n = len(group)
    for rank, i in enumerate(order):
        p = group[i]
        bucket = rank * N_BUCKETS // n
        bit = np.uint32(1 << bucket)
        for k in range(m):
            # Pipeline slot k is applied k steps after the oldest check, so
            # tables[:, k] holds the pair at depth m-1-k from the pattern
            # end: candidate(t) = AND_k AND_h tables[h, k][hash_h(pair at
            # t-(m-1-k))], and the pair at depth d ends exactly at byte t-d.
            d = m - 1 - k
            b0, b1 = p[len(p) - 2 - d], p[len(p) - 1 - d]
            for h in range(n_hashes):
                tables[h, k, int(pair_hash(b0, b1, domain, which=h))] |= bit
    return tables


def _fp_estimate(tables: np.ndarray) -> float:
    """Expected candidate probability per byte on uniform random pairs:
    sum over buckets of prod over (position, hash) of that bucket's
    density (the two hashes of one pair are treated as independent)."""
    n_hashes, m, domain = tables.shape
    bits = (tables[:, :, :, None] >> np.arange(N_BUCKETS, dtype=np.uint32)) & 1
    dens = bits.sum(axis=2) / domain  # (n_hashes, m, N_BUCKETS)
    return float(np.prod(dens.reshape(n_hashes * m, N_BUCKETS), axis=0).sum())


def _compile_group(
    group: list[bytes], m: int, fp_budget: float, max_banks: int
) -> list[FdrBank]:
    """Pick (domain, n_hashes, n_banks) for one length-stratified group by
    minimizing the total-cost model (scan + expected confirm) subject to
    the FP budget, with a statistical prescreen so only the most promising
    few configurations pay for an exact table build."""

    def total_ps(cost_units: float, fp: float) -> float:
        return cost_units * COST_PS_PER_UNIT + fp * CONFIRM_PS_PER_CANDIDATE

    prescreen = []
    for domain in DOMAINS:
        for n_hashes in (1, 2):
            if n_hashes * m * (domain // 128) > MAX_GATHERS:
                continue  # measured Mosaic compile ceiling
            for n_banks in (1, 2, 4, 8, 16, 32):
                if n_banks > max_banks or (n_banks > 1 and len(group) < n_banks * 4):
                    continue
                cost = n_banks * m * n_hashes * (2 * (domain // 128) + 2)
                # statistical density: distinct-pair collisions into D slots
                per_bucket = max(1, -(-len(group) // (n_banks * N_BUCKETS)))
                d_est = 1.0 - (1.0 - 1.0 / domain) ** per_bucket
                fp_est = n_banks * N_BUCKETS * d_est ** (m * n_hashes)
                prescreen.append(
                    (total_ps(cost, fp_est), cost, domain, n_hashes, n_banks)
                )
    prescreen.sort()
    # exact-build set: best few by estimated total, plus the lowest
    # estimated-FP configs so a tight explicit budget stays satisfiable
    by_fp = sorted(
        prescreen,
        key=lambda t: t[0] - t[1] * COST_PS_PER_UNIT,  # confirm term only
    )
    chosen, seen = [], set()
    for entry in prescreen[:4] + by_fp[:2]:
        if entry[2:] not in seen:
            seen.add(entry[2:])
            chosen.append(entry)
    best: tuple[float, float, list[FdrBank]] | None = None  # (key0, key1, banks)

    def try_config(cost, domain, n_hashes, n_banks):
        nonlocal best
        shards = [group[i::n_banks] for i in range(n_banks)]
        banks = []
        for shard in shards:
            tables = _bank_tables(shard, m, domain, n_hashes)
            banks.append(
                FdrBank(
                    m=m,
                    domain=domain,
                    tables=tables,
                    patterns=shard,
                    fp_per_byte=_fp_estimate(tables),
                )
            )
        fp = sum(b.fp_per_byte for b in banks)
        total = total_ps(cost, fp)
        # prefer configurations within budget; among those, min total cost;
        # if none fits the budget, min FP keeps the confirm bounded
        key = (0, total) if fp <= fp_budget else (1, fp)
        if best is None or key < (best[0], best[1]):
            best = (key[0], key[1], banks)

    for _, cost, domain, n_hashes, n_banks in chosen:
        try_config(cost, domain, n_hashes, n_banks)
    if best is not None and best[0] == 1:
        # Nothing in the prescreen's picks met the budget.  The statistical
        # estimate can misrank skewed sets (duplicate tails), so before
        # returning an over-budget config — or letting compile_fdr give up
        # and strand the engine on the slow DFA path — exhaustively build
        # the remaining configurations (the old guarantee: if any candidate
        # satisfies the budget, it is found).
        for entry in prescreen:
            if entry[2:] not in seen:
                seen.add(entry[2:])
                try_config(*entry[1:])
    assert best is not None
    return best[2]


def compile_fdr(
    patterns: list[str | bytes],
    *,
    ignore_case: bool = False,
    fp_budget_per_byte: float = 2e-4,
    max_banks: int = 32,
) -> FdrModel:
    """Compile a literal set (every literal >= 2 bytes) into filter banks.

    Patterns are stratified by length class so each group's window is as
    long as its shortest member allows (m = min(len)-1, capped at MAX_M);
    groups too small to be worth a device pass merge into the next shorter
    window.  Raises FdrError for sets this filter cannot host (the engine
    routes those members to the exact DFA-bank path instead).
    """
    norm = _normalize(patterns, ignore_case)
    if not norm:
        raise FdrError("empty pattern set")
    if any(len(p) < 2 for p in norm):
        raise FdrError("FDR needs literals >= 2 bytes")

    groups: dict[int, list[bytes]] = {}
    for p in norm:
        groups.setdefault(min(MAX_M, len(p) - 1), []).append(p)
    # merge small groups downward (their patterns still satisfy smaller m)
    for m in sorted(groups.keys(), reverse=True):
        if len(groups) > 1 and len(groups[m]) < 32:
            smaller = [k for k in groups if k < m]
            if smaller:
                groups[max(smaller)].extend(groups.pop(m))

    budget_each = fp_budget_per_byte / len(groups)
    banks: list[FdrBank] = []
    for m in sorted(groups.keys(), reverse=True):
        banks.extend(_compile_group(groups[m], m, budget_each, max_banks))
    model = FdrModel(banks=banks, ignore_case=ignore_case, n_patterns=len(norm))
    if model.fp_per_byte > FP_CEILING_PER_BYTE:
        raise FdrError(
            f"set too dense to filter: best candidate rate "
            f"{model.fp_per_byte:.3g}/byte > {FP_CEILING_PER_BYTE:g}"
        )
    return model


# ------------------------------------------------------------------ reference

def reference_candidates(bank: FdrBank, data: bytes) -> np.ndarray:
    """NumPy oracle of the device filter for one bank: candidate end offsets
    (i+1 convention, like models/dfa.reference_scan) over a single stripe.

    Mirrors the kernel exactly, including the all-ones pipeline seed at the
    stripe start (conservative: early positions over-report rather than
    miss, and the engine host-confirms candidates anyway).
    """
    arr = np.frombuffer(data, dtype=np.uint8).astype(np.int64)
    n = arr.size
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    prev = np.concatenate([[0], arr[:-1]])
    masks = None  # (m, n) uint32: AND over hashes of per-position reach
    for h_i in range(bank.n_hashes):
        h = pair_hash(prev, arr, bank.domain, which=h_i)
        got = bank.tables[h_i][:, h]
        masks = got if masks is None else (masks & got)
    ones = np.uint32(0xFFFFFFFF)
    # pipeline: V_0(t) = masks[0, t]; V_k(t) = V_{k-1}(t-1) & masks[k, t]
    Vs = np.empty((bank.m, n), dtype=np.uint32)
    Vs[0] = masks[0]
    for k in range(1, bank.m):
        shifted = np.concatenate([[ones], Vs[k - 1][:-1]])
        Vs[k] = shifted & masks[k]
    return np.nonzero(Vs[bank.m - 1] != 0)[0].astype(np.int64) + 1


def reference_candidates_model(model: FdrModel, data: bytes) -> np.ndarray:
    """Union of per-bank candidate end offsets."""
    if model.ignore_case:
        data = bytes(data).lower()
    outs = [reference_candidates(b, data) for b in model.banks]
    return np.unique(np.concatenate(outs)) if outs else np.zeros(0, dtype=np.int64)


def exact_match_lines(patterns: list[bytes], data: bytes, ignore_case: bool) -> set[int]:
    """Simple oracle for tests: 1-based lines containing any literal."""
    hay = data.lower() if ignore_case else data
    needles = [p.lower() if ignore_case else p for p in patterns]
    out = set()
    for i, line in enumerate(hay.split(b"\n"), 1):
        if any(nd in line for nd in needles):
            out.add(i)
    return out

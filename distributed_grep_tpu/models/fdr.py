"""FDR-style bucketed literal-set filter model (Hyperscan's large-set idea,
re-derived for the TPU VPU's lane-gather primitive).

Large literal sets (BASELINE.json configs 3 and 5 — grep -f / Snort-style
rulesets) are the one workload where the reference's per-line regex loop
(/root/reference/application/grep.go:20-30) has no small automaton: an
Aho-Corasick DFA over 10k patterns has ~60k states, and a per-byte table
gather at that size is the XLA scan path's ~0.1 GB/s cliff.  Hyperscan's
answer is FDR: superimpose the set into a few *buckets*, filter the stream
with shift-AND over per-position reach tables, and confirm rare candidates
exactly.  This module is that idea rebuilt around what the TPU can do fast.

Design (v3 — round-2 final: per-check domains + cell-snapped clustering):

* 32 buckets — one uint32 per lane, the tile shape every kernel here uses.
* One *suffix window* per bank: every member is represented by its last
  ``m+1`` bytes (a true match always contains its suffix, so candidates
  stay a superset; the exact confirm restores precision).
* Reach tables indexed by a pair-domain hash ``h = ((b0*a) ^ (b1*b)) &
  (D-1)`` of two consecutive bytes.  **Each check chooses its own domain**
  (the kernel's lane-gather covers 128 entries per op, so a check costs
  D/128 gathers) — the unit of currency is the gather, and the information
  argument says a check's false-positive density depends only on
  ``n / (32 * D)``, i.e. on table bits, making the cost/density frontier
  flat in D.  What breaks the tie is the clustered check:
* **Cell-snapped clustered bucket assignment** — members are sorted by
  their final-pair hash at D=128 and buckets are runs of whole hash
  *cells* (a cell is never split across buckets).  Each bucket's density
  at the clustered check is then exactly its cell count / 128, and the
  *sum* over buckets is exactly 1 — independent of set size.  Because
  that property holds at ANY domain, the clustered check runs at the
  minimum D=128: **one gather buys a Σ-density-1 check** that would cost
  an unclustered plan ~log(32·d)/log(1/d) extra checks.  (v2 clustered at
  the filler domain and paid 4 gathers for it; that plus rank-straddled
  cells is where the 28-gather plan went.)
* A tunable **check plan**: ``(slot, family, domain)`` lookups.  Slot k
  covers the byte pair at depth m-1-k from the window end; two hash
  families (HASHES) give up to 2 checks per slot; checks sharing a slot
  AND together before entering the pipeline.  The tuner enumerates filler
  domain × lookup count × bank count and minimizes measured total cost
  (device gathers + expected confirm, overlapped), with expected candidate
  rates computed exactly from the built tables (``_fp_of_tables``).

For the 10k-pattern config-5 set this lands on clustered@128 + 3×D512 +
2×D256 = 17 gathers/byte at analytic fp ~2.7e-2 (measured ~12.2
GB/s/chip) — vs v2's 28 gathers at fp 9e-3 (7.8 GB/s) — because the
confirm side (native bloom-filtered suffix probe, utils/native.ConfirmSet)
got cheap enough to absorb the higher candidate rate while staying hidden
behind the device scan given the priced CONFIRM_THREADS host threads.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

NL = 0x0A
N_BUCKETS = 32
MAX_DEPTHS = 6  # pipeline slots; window = depths + 1 <= 7 bytes
DOMAINS = (128, 256, 512, 1024)  # kernel gathers per check = D / 128
CLUSTER_DOMAIN = 128  # the clustered check's domain: Σ-density 1 at 1 gather
# Two independent pair hash families; ANDing lookups of both families at
# one slot squares that slot's density (d -> d0*d1), which beats adding
# banks for dense full-alphabet sets.
HASHES = ((37, 101), (171, 59))
# Sets whose best achievable EXPECTED candidate rate (analytic x bias) is
# still above this are not worth filtering: beyond ~0.1/byte the host-side
# sparse decode + confirm legs stop hiding behind the device scan even
# with the full thread fan, and the exact DFA banks win.  compile_fdr
# raises and the engine keeps those instead.
FP_CEILING_PER_BYTE = 1e-1

# Total-cost model for the tuner, per scanned byte, calibrated on TPU v5e
# (2026-07-30, probe in ops/pallas_fdr.py docstring): the 128-entry lane
# gather issues at ~4.5 cycles per (8,128) vreg and is the kernel's
# bottleneck resource — ~4.7 ps per gather per byte at unroll=4.  One
# expected candidate costs ~4 ns of confirm (measured: the native
# bloom-filtered suffix probe, utils/native.ConfirmSet, ~3.8-4.3
# ns/candidate single-thread on the build host over sorted offsets at
# config-5 densities).  The engine overlaps the confirm of segment i with
# the device scan of segment i+1, so the steady-state per-byte cost is
# max(scan, confirm) plus a small non-overlapped share — the objective
# below — not their sum.
COST_PS_PER_GATHER = 4.7
# Measured on the real config-5 run (2026-07-30, engine.stats): FDR-biased
# candidates confirm at ~8.6 ns each single-thread — worse than the 4 ns
# random-offset microbench because filtered candidates pass the bloom and
# walk the probe path more often.  The engine's ConfirmSet fans the
# candidate array over min(8, cpu) threads; the tuner prices against
# CONFIRM_THREADS of them (default 8 — any real TPU host has that; set
# DGREP_CONFIRM_THREADS for constrained hosts, e.g. 1 on this 1-core
# build VM, which shifts the tuner toward more device gathers).
CONFIRM_PS_PER_CANDIDATE = 8_600.0


def _confirm_threads() -> int:
    """Confirm threads the tuner prices against.  Defaults to 8 — the
    runtime confirm fans candidates over min(8, cpu) threads
    (utils/native.ConfirmSet), and every real TPU host has >=8 cores, so
    the default prices exactly what will run in deployment.  Constrained
    workers should set DGREP_CONFIRM_THREADS to their core count (e.g. 1
    on the 1-core build VM), which shifts the tuner toward more device
    gathers / fewer candidates so a weak host's confirm still keeps up."""
    try:
        return max(1, int(os.environ.get("DGREP_CONFIRM_THREADS", "8")))
    except ValueError:
        return 8


CONFIRM_THREADS = _confirm_threads()
# The analytic fp model treats checks as independent; measured candidate
# rates run ~2.4x higher (same-pair cross-family checks are positively
# correlated through the shared pattern set — oracle-verified on the 10k
# config-5 set: model 0.019/byte vs 0.047 measured).  The tuner prices
# confirm with this bias; the analytic value still ranks plans.
EMPIRICAL_FP_BIAS = 2.5
OVERLAP_RESIDUE = 0.2  # fraction of the smaller leg that fails to overlap
# Kernel compile ceiling: lane-gathers per byte step.  Round-5 probe
# (benchmarks/probe_gather_ceiling.py, v5e 2026-08-01): 44/48/56/64-gather
# m=6 plans (fillers at D=1024) ALL compile and run bit-exact vs the
# NumPy reference at both production unrolls, with throughput tracking
# the ~4.7 ps/gather model (64 gathers -> 3.3-3.7 GB/s) — the old
# 40-gather cap (itself replacing an unroll-32-artifact 24) was
# conservative, not a hardware wall.  64 is the new probed bound.
MAX_GATHERS = 64
# The native MT host scanner is the engine's routing alternative for
# FDR-rejected sets: ~0.33 GB/s/core measured on this VM's AC/DFA table
# walk (BASELINE.md "native MT host scanner" row), scaling ~linearly
# with the confirm-thread fan.  With MAX_GATHERS=64 the plan menu now
# admits filters big enough to price BELOW that host fan, so
# eligibility must gate on scan cost too, not just candidate rate —
# a filter that scans slower than the host's exact scanner is not
# worth the device no matter how clean its candidate stream is.
NATIVE_SCAN_GBPS_PER_THREAD = 0.33


@dataclass(frozen=True)
class Pricing:
    """The tuner's cost constants as one value, so the runtime can replace
    the compile-time assumptions with MEASURED numbers (self-calibration,
    VERDICT r2 item 3): the engine probes ConfirmSet at init (catching e.g.
    the ~100x-slower Python-fallback confirm on hosts without the native
    lib) and retunes from real engine.stats after the first scan."""

    confirm_ps_per_candidate: float  # single-thread wall, ps
    confirm_threads: int
    fp_bias: float  # measured/analytic candidate-rate ratio
    overlap_residue: float
    # Active chips sharing this host's confirm thread fan (VERDICT r3 item
    # 1).  The scan leg scales with chips (each chip scans its own byte
    # stream / lane shard) while the confirm stream rides ONE host's
    # threads, so per scanned byte the confirm leg costs n_chips/threads —
    # on a 4-chip host a plan whose confirm hid behind the scan at 8
    # threads stops hiding at the 2-thread-per-chip share, and the tuner
    # should buy more device gathers instead.
    n_chips: int = 1

    def confirm_wall_ps(self, fp_per_byte: float) -> float:
        """Expected per-byte confirm wall given an analytic fp rate,
        relative to one chip's scan timeline (threads are shared across
        the host's active chips)."""
        return (
            fp_per_byte * self.fp_bias
            * self.confirm_ps_per_candidate
            * self.n_chips / self.confirm_threads
        )

    def total_ps(self, scan_ps: float, fp_per_byte: float) -> float:
        confirm = self.confirm_wall_ps(fp_per_byte)
        return max(scan_ps, confirm) + self.overlap_residue * min(scan_ps, confirm)


def default_pricing() -> Pricing:
    """Current module constants (reads globals at call time so tests can
    monkeypatch them)."""
    return Pricing(
        confirm_ps_per_candidate=CONFIRM_PS_PER_CANDIDATE,
        confirm_threads=CONFIRM_THREADS,
        fp_bias=EMPIRICAL_FP_BIAS,
        overlap_residue=OVERLAP_RESIDUE,
    )


def probe_confirm_ps(confirm_set, n: int = 1 << 15, seed: int = 0,
                     n_threads: int = 1) -> float:
    """Measured wall ps/candidate of THIS host's ConfirmSet at the given
    thread fan on synthetic random candidates (~ms; run once per engine
    init at n_threads=1; the post-scan retune probes again at the actual
    fan to measure parallel efficiency instead of assuming ideal scaling).

    Random offsets under-represent the bloom-pass bias of real FDR
    candidates (~2x, see CONFIRM_PS_PER_CANDIDATE), so callers should gate
    retuning on a wide ratio — the probe exists to catch order-of-magnitude
    mispricing (missing native lib, exotic hosts), and the post-scan stats
    retune handles the fine constants."""
    import time

    rng = np.random.default_rng(seed)
    buf = rng.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes()
    ends = np.sort(rng.integers(8, len(buf), size=n)).astype(np.uint64)
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        confirm_set.confirm(buf, ends, n_threads=n_threads)
        best = min(best, time.perf_counter() - t0)
    return best / n * 1e12


def pair_hash(b0: np.ndarray | int, b1: np.ndarray | int, domain: int, which: int = 0):
    """The kernel's pair-domain hash — shared host/device definition.

    Domains are nested: ``pair_hash(..., D) == pair_hash(..., D') & (D-1)``
    for D <= D', which is what lets the kernel compute one hash per family
    and mask it down per check."""
    a, b = HASHES[which]
    return ((b0 * a) ^ (b1 * b)) & (domain - 1)


class FdrError(ValueError):
    pass


@dataclass(frozen=True)
class FdrBank:
    """One filter pass: a check plan over an m-slot pipeline.

    ``checks[i] = (slot, family, domain)``: lookup i probes ``tables[i]``
    (a (domain,) uint32 bucket-mask array) with hash family ``family`` of
    the byte pair at slot ``slot``; slot k is applied k steps after the
    oldest check, so it covers the pair at depth m-1-k from the window
    end.  Checks sharing a slot AND together before entering the
    pipeline."""

    m: int  # pipeline slots (window = m+1 bytes)
    checks: tuple[tuple[int, int, int], ...]  # (slot, family, domain)
    tables: tuple[np.ndarray, ...]  # per check: (domain,) uint32 bucket masks
    patterns: list[bytes]  # normalized suffix members (for debugging/repr)
    fp_per_byte: float  # expected candidate rate on uniform bytes

    @property
    def n_checks(self) -> int:
        return len(self.checks)

    @property
    def domain(self) -> int:
        """Largest check domain (kernel hash width)."""
        return max(d for _, _, d in self.checks)

    @property
    def families(self) -> tuple[int, ...]:
        return tuple(sorted({f for _, f, _ in self.checks}))

    @property
    def total_gathers(self) -> int:
        return sum(d // 128 for _, _, d in self.checks)

    def scan_cost_ps(self) -> float:
        """Modeled per-byte device cost (gathers dominate)."""
        return COST_PS_PER_GATHER * self.total_gathers


@dataclass(frozen=True)
class FdrModel:
    banks: list[FdrBank]
    ignore_case: bool
    n_patterns: int

    @property
    def fp_per_byte(self) -> float:
        return float(sum(b.fp_per_byte for b in self.banks))

    def scan_cost_ps(self) -> float:
        return sum(b.scan_cost_ps() for b in self.banks)

    @property
    def window(self) -> int:
        """Max filter window — candidate misses are confined to the first
        window-1 bytes of a stripe (the engine's boundary stitching)."""
        return max(b.m for b in self.banks) + 1


def _normalize(patterns: list[str | bytes], ignore_case: bool) -> list[bytes]:
    out: list[bytes] = []
    for p in patterns:
        b = p.encode("utf-8", "surrogateescape") if isinstance(p, str) else bytes(p)
        if not b:
            raise FdrError("empty literal in pattern set")
        if NL in b:
            raise FdrError("literal contains '\\n' — not representable per-line")
        out.append(b.lower() if ignore_case else b)
    return out


def _bucket_of(group: list[bytes]) -> np.ndarray:
    """Cell-snapped clustered bucket assignment.

    Sort members by their final-pair hash at CLUSTER_DOMAIN and pack whole
    hash cells into buckets targeting equal member counts.  Because no
    cell is split, bucket b's density at the clustered check is exactly
    cells(b)/CLUSTER_DOMAIN and Σ_b density_b == 1 (every cell belongs to
    exactly one bucket) — rank-range assignment (v2) leaked ~N_BUCKETS
    straddled cells, i.e. a 1.2x fp factor at D=128."""
    n = len(group)
    cells = [int(pair_hash(p[-2], p[-1], CLUSTER_DOMAIN)) for p in group]
    order = sorted(range(n), key=lambda i: (cells[i], group[i]))
    bucket = np.zeros(n, dtype=np.int64)
    b = 0
    for rank, i in enumerate(order):
        want = min(N_BUCKETS - 1, rank * N_BUCKETS // n)
        if want > b and cells[i] != cells[order[rank - 1]]:
            b = want
        bucket[i] = b
    return bucket


def _pair_arrays(group: list[bytes], m: int) -> tuple[np.ndarray, np.ndarray]:
    """(m, n) arrays of the byte pair at each depth d from the suffix end."""
    b0 = np.empty((m, len(group)), dtype=np.int64)
    b1 = np.empty((m, len(group)), dtype=np.int64)
    for d in range(m):
        for i, p in enumerate(group):
            b0[d, i] = p[len(p) - 2 - d]
            b1[d, i] = p[len(p) - 1 - d]
    return b0, b1


def _build_tables(
    group: list[bytes],
    bucket: np.ndarray,
    m: int,
    checks: tuple[tuple[int, int, int], ...],
    pair_cache: dict | None = None,
) -> tuple[np.ndarray, ...]:
    """Reach tables for one check plan (vectorized over members)."""
    if pair_cache is None or "pairs" not in pair_cache:
        pairs = _pair_arrays(group, m)
        if pair_cache is not None:
            pair_cache["pairs"] = pairs
    else:
        pairs = pair_cache["pairs"]
    b0, b1 = pairs
    bits = (np.uint32(1) << bucket.astype(np.uint32)).astype(np.uint32)
    out = []
    for slot, fam, domain in checks:
        key = (slot, fam, domain)
        if pair_cache is not None and key in pair_cache:
            out.append(pair_cache[key])
            continue
        d = m - 1 - slot
        idx = pair_hash(b0[d], b1[d], domain, which=fam)
        t = np.zeros(domain, dtype=np.uint32)
        np.bitwise_or.at(t, idx, bits)
        if pair_cache is not None:
            pair_cache[key] = t
        out.append(t)
    return tuple(out)


def _fp_of_tables(tables: tuple[np.ndarray, ...]) -> float:
    """Expected candidate probability per byte on uniform random pairs:
    sum over buckets of prod over checks of that bucket's density (checks
    are treated as independent — different pairs, or different hash
    families of one pair)."""
    prod = np.ones(N_BUCKETS, dtype=np.float64)
    for t in tables:
        bits = (t[:, None] >> np.arange(N_BUCKETS, dtype=np.uint32)) & 1
        prod *= bits.sum(axis=0) / t.shape[0]
    return float(prod.sum())


def _filler_slots(m: int) -> list[tuple[int, int]]:
    """Filler priority: family 0 from the deepest unused slot down, then
    family 1 (slot m-1 first: it shares the clustered pair and rides
    residual clustering)."""
    return [(k, 0) for k in range(m - 2, -1, -1)] + [
        (k, 1) for k in range(m - 1, -1, -1)
    ]


def _plans(m: int):
    """All candidate check plans: the cell-snapped clustered check (slot
    m-1, family 0) at CLUSTER_DOMAIN plus every multiset of filler domains
    (largest domains assigned to the highest-priority fillers).  Mixed
    domains matter: the gather is the unit of cost, and e.g. swapping one
    D=512 filler for D=256 drops 2 gathers for a ~1.5x fp factor — the
    right trade exactly when the confirm has slack."""
    from itertools import combinations_with_replacement

    slots = _filler_slots(m)
    for n_fill in range(1, len(slots) + 1):
        for doms in combinations_with_replacement(DOMAINS, n_fill):
            ds = sorted(doms, reverse=True)
            yield ((m - 1, 0, CLUSTER_DOMAIN),) + tuple(
                (k, f, d) for (k, f), d in zip(slots, ds)
            )


def _compress_banks(banks: list[FdrBank]) -> list[FdrBank]:
    """Drop pipeline slots no check probes (a small plan on a long window
    checks only shallow depths — e.g. the 8-word config-2 plan probes
    depths {0,1} but inherited m=6 from the members' length): slots with
    no check are pure shift-through (V_k(t) = V_{k-1}(t-1)), so remapping
    every check to slot m'-1-depth with m' = max depth + 1 yields a
    candidate stream identical except for LESS stripe-head over-report
    (the all-ones seed covers m' positions instead of m) while the kernel
    carries m' registers instead of m.  Tables are depth-keyed
    (d = m-1-slot) and therefore reused unchanged.  Probed on v5e
    (2026-07-30, config-2 A/B): throughput-neutral — Mosaic already
    sinks the dead shift-throughs — so this is kept for the smaller VMEM
    scratch, the shorter over-report window (fewer boundary confirms),
    and plan-shape honesty (a 2-depth plan now SAYS m=2)."""
    out = []
    for b in banks:
        depths = [b.m - 1 - slot for slot, _, _ in b.checks]
        m_eff = max(depths) + 1
        if m_eff == b.m:
            out.append(b)
            continue
        checks = tuple(
            (m_eff - 1 - d, fam, dom)
            for d, (_, fam, dom) in zip(depths, b.checks)
        )
        out.append(FdrBank(
            m=m_eff, checks=checks, tables=b.tables,
            patterns=b.patterns, fp_per_byte=b.fp_per_byte,
        ))
    return out


def _compile_group(
    group: list[bytes], m: int, fp_budget: float, max_banks: int = 4,
    pricing: Pricing | None = None,
) -> list[FdrBank]:
    """Pick (fill domain, n_lookups, n_banks) for one window group by
    minimizing the total-cost model (scan + expected confirm, overlapped),
    preferring budget-satisfying configurations when any exists."""
    pricing = pricing or default_pricing()
    total_ps = pricing.total_ps

    best: tuple[tuple, list[FdrBank]] | None = None
    for n_banks in (1, 2, 4):
        if n_banks > max_banks or (n_banks > 1 and len(group) < n_banks * N_BUCKETS):
            continue
        shards = [group[i::n_banks] for i in range(n_banks)]
        buckets = [_bucket_of(s) for s in shards]
        caches = [{} for _ in shards]
        for plan in _plans(m):
            gathers = sum(d // 128 for _, _, d in plan)
            if gathers > MAX_GATHERS:
                continue  # outside the kernel's probed compile ceiling
            # The scan leg alone lower-bounds total_ps (total = max(scan,
            # confirm) + residue*min >= scan), so once a within-budget best
            # exists, any plan whose gathers already cost more than that
            # best's TOTAL cannot win — skip building its tables (the
            # expensive step; ~halves the 10k-set tuner's compile time).
            if (
                best is not None
                and best[0][0] == 0
                and COST_PS_PER_GATHER * gathers * len(shards) > best[0][1]
            ):
                continue
            banks = []
            for shard, bucket, cache in zip(shards, buckets, caches):
                tabs = _build_tables(shard, bucket, m, plan, cache)
                banks.append(
                    FdrBank(
                        m=m,
                        checks=plan,
                        tables=tabs,
                        patterns=shard,
                        fp_per_byte=_fp_of_tables(tabs),
                    )
                )
            fp = sum(b.fp_per_byte for b in banks)
            cost = sum(b.scan_cost_ps() for b in banks)
            # prefer configurations within budget; among those, min
            # total cost; if none fits, min FP bounds the confirm.  The
            # budget bounds the EXPECTED rate (analytic x bias), the same
            # quantity the compile_fdr ceiling gates on.
            within = fp * pricing.fp_bias <= fp_budget
            key = (0, total_ps(cost, fp)) if within else (1, fp, cost)
            if best is None or key < best[0]:
                best = (key, banks)
    assert best is not None
    return _compress_banks(best[1])


def compile_fdr(
    patterns: list[str | bytes],
    *,
    ignore_case: bool = False,
    fp_budget_per_byte: float = FP_CEILING_PER_BYTE,
    max_banks: int = 4,
    pricing: Pricing | None = None,
) -> FdrModel:
    """Compile a literal set (every literal >= 2 bytes) into filter banks.

    The window is set by the shortest member (suffix truncation makes every
    longer member representable in it).  When the set's lengths are mixed
    enough that splitting pays — a long-window group gets more slots and a
    short group stops poisoning it — the tuner compares every two-group
    split against the single-bank compile by total cost.  Raises FdrError
    for sets this filter cannot host (the engine routes those to the exact
    DFA-bank path instead)."""
    pricing = pricing or default_pricing()
    norm = _normalize(patterns, ignore_case)
    if not norm:
        raise FdrError("empty pattern set")
    if any(len(p) < 2 for p in norm):
        raise FdrError("FDR needs literals >= 2 bytes")

    def window_of(subset: list[bytes]) -> int:
        return min(MAX_DEPTHS + 1, min(len(p) for p in subset))

    def group_cost(banks: list[FdrBank]) -> float:
        scan = sum(b.scan_cost_ps() for b in banks)
        return pricing.total_ps(scan, sum(b.fp_per_byte for b in banks))

    candidates: list[list[FdrBank]] = []
    single = _compile_group(
        norm, window_of(norm) - 1, fp_budget_per_byte, max_banks, pricing
    )
    candidates.append(single)
    lengths = sorted({min(len(p), MAX_DEPTHS + 1) for p in norm})
    for t in lengths[1:]:
        short = [p for p in norm if min(len(p), MAX_DEPTHS + 1) < t]
        long_ = [p for p in norm if min(len(p), MAX_DEPTHS + 1) >= t]
        if len(short) < N_BUCKETS or len(long_) < N_BUCKETS:
            continue
        candidates.append(
            _compile_group(short, window_of(short) - 1, fp_budget_per_byte / 2,
                           max_banks, pricing)
            + _compile_group(long_, window_of(long_) - 1, fp_budget_per_byte / 2,
                             max_banks, pricing)
        )
    banks = min(candidates, key=group_cost)
    from distributed_grep_tpu.utils.native import native_available

    scan_ps = sum(b.scan_cost_ps() for b in banks)
    device_gbps = pricing.n_chips * 1000.0 / scan_ps if scan_ps else float("inf")
    native_gbps = NATIVE_SCAN_GBPS_PER_THREAD * pricing.confirm_threads
    # Only cede to the host when the host scanner actually exists: on a
    # native-less install the engine's FdrError fallback is the ~0.1 GB/s
    # XLA DFA-bank path (_route_native no-ops there), and even a
    # 100-gather filter beats that by ~20x.
    if device_gbps < native_gbps and native_available():
        raise FdrError(
            f"cheapest filter plan scans at {device_gbps:.1f} GB/s "
            f"({sum(b.total_gathers for b in banks)} gathers x "
            f"{pricing.n_chips} chip(s)) — below the ~{native_gbps:.1f} GB/s "
            f"native host fan; the exact host scanner wins this set"
        )
    model = FdrModel(banks=banks, ignore_case=ignore_case, n_patterns=len(norm))
    # gate on the EXPECTED REAL rate (analytic x measured bias), like the
    # cost model — an analytic-only gate would admit sets whose true
    # candidate rate is in the confirm-dominates regime
    if model.fp_per_byte * pricing.fp_bias > FP_CEILING_PER_BYTE:
        raise FdrError(
            f"set too dense to filter: expected candidate rate "
            f"{model.fp_per_byte * pricing.fp_bias:.3g}/byte "
            f"(analytic x{pricing.fp_bias:g} bias) > {FP_CEILING_PER_BYTE:g}"
        )
    return model


# ------------------------------------------------------------------ reference

def reference_candidates(bank: FdrBank, data: bytes) -> np.ndarray:
    """NumPy oracle of the device filter for one bank: candidate end offsets
    (i+1 convention, like models/dfa.reference_scan) over a single stripe.

    Mirrors the kernel exactly, including the all-ones pipeline seed at the
    stripe start (conservative: early positions over-report rather than
    miss, and the engine confirms candidates exactly anyway).
    """
    arr = np.frombuffer(data, dtype=np.uint8).astype(np.int64)
    n = arr.size
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    prev = np.concatenate([[0], arr[:-1]])
    ones = np.uint32(0xFFFFFFFF)
    slot_masks = np.full((bank.m, n), ones, dtype=np.uint32)
    for i, (slot, fam, domain) in enumerate(bank.checks):
        h = pair_hash(prev, arr, domain, which=fam)
        slot_masks[slot] &= bank.tables[i][h]
    # pipeline: V_0(t) = masks[0, t]; V_k(t) = V_{k-1}(t-1) & masks[k, t]
    Vs = np.empty((bank.m, n), dtype=np.uint32)
    Vs[0] = slot_masks[0]
    for k in range(1, bank.m):
        shifted = np.concatenate([[ones], Vs[k - 1][:-1]])
        Vs[k] = shifted & slot_masks[k]
    return np.nonzero(Vs[bank.m - 1] != 0)[0].astype(np.int64) + 1


def reference_candidates_model(model: FdrModel, data: bytes) -> np.ndarray:
    """Union of per-bank candidate end offsets."""
    if model.ignore_case:
        data = bytes(data).lower()
    outs = [reference_candidates(b, data) for b in model.banks]
    return np.unique(np.concatenate(outs)) if outs else np.zeros(0, dtype=np.int64)


def exact_match_lines(patterns: list[bytes], data: bytes, ignore_case: bool) -> set[int]:
    """Simple oracle for tests: 1-based lines containing any literal."""
    hay = data.lower() if ignore_case else data
    needles = [p.lower() if ignore_case else p for p in patterns]
    out = set()
    for i, line in enumerate(hay.split(b"\n"), 1):
        if any(nd in line for nd in needles):
            out.add(i)
    return out

"""FDR-style bucketed literal-set filter model (Hyperscan's large-set idea,
re-derived for the TPU VPU's lane-gather primitive).

Large literal sets (BASELINE.json configs 3 and 5 — grep -f / Snort-style
rulesets) are the one workload where the reference's per-line regex loop
(/root/reference/application/grep.go:20-30) has no small automaton: an
Aho-Corasick DFA over 10k patterns has ~60k states, and a per-byte table
gather at that size is the XLA scan path's ~0.1 GB/s cliff.  Hyperscan's
answer is FDR: superimpose the set into a few *buckets*, filter the stream
with shift-AND over per-position reach tables, and confirm rare candidates
exactly.  This module is that idea rebuilt around what the TPU can do fast.

Design (v2 — the round-2 redesign that took config 5 off its 5-pass cost):

* 32 buckets — one uint32 per lane, the tile shape every kernel here uses.
* One *suffix window* per bank: every member is represented by its last
  ``m+1`` bytes (a true match always contains its suffix, so candidates
  stay a superset; the exact confirm restores precision).  No per-length
  bank fan-out — one device pass hosts the whole set.
* Reach tables indexed by a pair-domain hash ``h = ((b0*a) ^ (b1*b)) &
  (D-1)`` of two consecutive bytes, D <= 512 (the kernel's lane-gather
  covers 128 entries per op, D/128 gathers per lookup).
* **Clustered bucket assignment** — the key density trick: members are
  sorted by their final-pair hash and buckets are rank ranges, so each
  bucket covers a contiguous ~D/32 slice of hash space at the final-pair
  check.  That one check's bucket density is ~1/32 *independent of set
  size* (vs ~n_bucket/D for an unclustered check): for a 10k set it is
  worth ~4.4 unclustered lookups for the price of one.
* A tunable **check plan**: a list of (pipeline slot, hash family) table
  lookups.  Slot k checks the byte pair at depth m-1-k from the window
  end; two independent hash families (HASHES) give up to 2 checks per
  slot.  The tuner picks how many lookups to spend (more lookups = lower
  candidate rate = more device time), minimizing measured total cost
  (device scan + expected confirm) rather than chasing a fixed FP.

The expected candidate rate is computed exactly from the built tables
(``_fp_of_stack``), so the clustering win is measured, not assumed.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

NL = 0x0A
N_BUCKETS = 32
MAX_DEPTHS = 6  # pipeline slots; window = depths + 1 <= 7 bytes
DOMAINS = (128, 256, 512)  # kernel gathers per lookup = D / 128
# Two independent pair hash families; ANDing lookups of both families at
# one slot squares that slot's density (d -> d0*d1), which beats adding
# banks for dense full-alphabet sets.
HASHES = ((37, 101), (171, 59))
# Sets whose best achievable candidate rate is still above this are not
# worth filtering (the confirm would dominate): compile_fdr raises and the
# engine keeps the exact DFA banks instead.
FP_CEILING_PER_BYTE = 2e-2

# Total-cost model for the tuner, per scanned byte, calibrated on TPU v5e
# (2026-07-30, probe recorded in ops/pallas_fdr.py docstring): a merged
# one-pass kernel runs at ~56/L GB/s for L D=512 lookups (smaller domains
# cost proportionally fewer gathers), i.e. ~17.9 ps per lookup-unit.  One
# expected candidate costs ~9 ns of confirm (measured: the native
# suffix-hash probe, utils/native.ConfirmSet, 7.5 ns/candidate
# single-thread on this host's 10k-set over sorted uniform offsets; the
# margin covers FDR candidates being hash-biased toward slot hits, which
# walk pattern chains more often).  The engine overlaps the confirm
# of segment i with the device scan of segment i+1, so the steady-state
# per-byte cost is max(scan, confirm) plus a small non-overlapped share —
# the objective below — not their sum.
COST_PS_PER_LOOKUP = 17.9
LOOKUP_UNITS = {128: 0.3, 256: 0.55, 512: 1.0}
CONFIRM_PS_PER_CANDIDATE = 9_000.0
OVERLAP_RESIDUE = 0.2  # fraction of the smaller leg that fails to overlap
# Kernel compile ceiling: lane-gathers per byte step (= lookups * D/128).
# Probed on v5e at the kernel's unroll=8: 40 compiles and runs; the old
# 24-gather ceiling was an unroll-32 artifact (ops/pallas_fdr.py notes).
MAX_GATHERS = 40


def pair_hash(b0: np.ndarray | int, b1: np.ndarray | int, domain: int, which: int = 0):
    """The kernel's pair-domain hash — shared host/device definition."""
    a, b = HASHES[which]
    return ((b0 * a) ^ (b1 * b)) & (domain - 1)


class FdrError(ValueError):
    pass


@dataclass(frozen=True)
class FdrBank:
    """One filter pass: a check plan over an m-slot pipeline.

    ``checks[i] = (slot, family)``: lookup i probes ``tables[i]`` with hash
    family ``family`` of the byte pair at slot ``slot``; slot k is applied
    k steps after the oldest check, so it covers the pair at depth m-1-k
    from the window end.  Checks sharing a slot AND together before
    entering the pipeline."""

    m: int  # pipeline slots (window = m+1 bytes)
    domain: int  # table entries; D/128 lane-gathers per lookup
    checks: tuple[tuple[int, int], ...]  # (slot, family) per lookup
    tables: np.ndarray  # (n_checks, domain) uint32 bucket masks
    patterns: list[bytes]  # normalized suffix members (for debugging/repr)
    fp_per_byte: float  # expected candidate rate on uniform bytes

    @property
    def n_checks(self) -> int:
        return len(self.checks)

    @property
    def n_subtables(self) -> int:
        return self.domain // 128

    @property
    def families(self) -> tuple[int, ...]:
        return tuple(sorted({f for _, f in self.checks}))

    def scan_cost_ps(self) -> float:
        """Modeled per-byte device cost (lookups dominate)."""
        return COST_PS_PER_LOOKUP * LOOKUP_UNITS[self.domain] * self.n_checks


@dataclass(frozen=True)
class FdrModel:
    banks: list[FdrBank]
    ignore_case: bool
    n_patterns: int

    @property
    def fp_per_byte(self) -> float:
        return float(sum(b.fp_per_byte for b in self.banks))

    def scan_cost_ps(self) -> float:
        return sum(b.scan_cost_ps() for b in self.banks)

    @property
    def window(self) -> int:
        """Max filter window — candidate misses are confined to the first
        window-1 bytes of a stripe (the engine's boundary stitching)."""
        return max(b.m for b in self.banks) + 1


def _normalize(patterns: list[str | bytes], ignore_case: bool) -> list[bytes]:
    out: list[bytes] = []
    for p in patterns:
        b = p.encode("utf-8", "surrogateescape") if isinstance(p, str) else bytes(p)
        if not b:
            raise FdrError("empty literal in pattern set")
        if NL in b:
            raise FdrError("literal contains '\\n' — not representable per-line")
        out.append(b.lower() if ignore_case else b)
    return out


def _full_tables(group: list[bytes], m: int, domain: int) -> np.ndarray:
    """Build the full (2 families x m slots, domain) uint32 reach stack for
    one bank over the members' (m+1)-byte suffixes.

    Bucket assignment sorts members by their final-pair hash (family 0) and
    buckets are rank ranges — so the slot m-1 / family 0 check sees each
    bucket covering a contiguous ~domain/N_BUCKETS hash slice: its density
    is ~1/N_BUCKETS regardless of set size (the clustering trick).  Rows
    are ordered ``family * m + slot``.
    """
    order = sorted(
        range(len(group)),
        key=lambda i: (int(pair_hash(group[i][-2], group[i][-1], domain)), group[i]),
    )
    tables = np.zeros((2 * m, domain), dtype=np.uint32)
    n = len(group)
    for rank, i in enumerate(order):
        p = group[i]
        bucket = rank * N_BUCKETS // n
        bit = np.uint32(1 << bucket)
        for k in range(m):
            # Slot k covers the pair at depth m-1-k from the suffix end;
            # the pair at depth d ends exactly at byte t-d.
            d = m - 1 - k
            b0, b1 = p[len(p) - 2 - d], p[len(p) - 1 - d]
            for h in range(2):
                tables[h * m + k, int(pair_hash(b0, b1, domain, which=h))] |= bit
    return tables


def _fp_of_stack(stack: np.ndarray) -> float:
    """Expected candidate probability per byte on uniform random pairs:
    sum over buckets of prod over checks of that bucket's density (checks
    are treated as independent — different pairs, or different hash
    families of one pair)."""
    bits = (stack[:, :, None] >> np.arange(N_BUCKETS, dtype=np.uint32)) & 1
    dens = bits.sum(axis=1) / stack.shape[1]  # (n_checks, N_BUCKETS)
    return float(np.prod(dens, axis=0).sum())


def _plan(m: int, n_lookups: int) -> tuple[tuple[int, int], ...]:
    """Check plan for a lookup budget: first family 0 at every slot (slot
    m-1 — the final pair — is the clustered check and always included),
    then family 1 from the deepest slot down (slot m-1's family-1 density
    rides the residual clustering, measurably below an unclustered check)."""
    checks = [(k, 0) for k in range(m)]
    checks += [(k, 1) for k in range(m - 1, -1, -1)]
    if not 1 <= n_lookups <= 2 * m:
        raise ValueError(f"lookup budget {n_lookups} outside 1..{2 * m}")
    chosen = checks[:n_lookups]
    if (m - 1, 0) not in chosen:  # tiny budgets: keep the clustered check
        chosen[-1] = (m - 1, 0)
    return tuple(chosen)


def _compile_group(
    group: list[bytes], m: int, fp_budget: float, max_banks: int = 4
) -> list[FdrBank]:
    """Pick (domain, n_lookups, n_banks) for one window group by minimizing
    the total-cost model (scan + expected confirm), preferring
    budget-satisfying configurations when any exists."""

    def total_ps(cost_ps: float, fp: float) -> float:
        confirm = fp * CONFIRM_PS_PER_CANDIDATE
        return max(cost_ps, confirm) + OVERLAP_RESIDUE * min(cost_ps, confirm)

    best: tuple[tuple, list[FdrBank]] | None = None
    for n_banks in (1, 2, 4):
        if n_banks > max_banks or (n_banks > 1 and len(group) < n_banks * N_BUCKETS):
            continue
        shards = [group[i::n_banks] for i in range(n_banks)]
        for domain in DOMAINS:
            fulls = [_full_tables(s, m, domain) for s in shards]
            for n_lookups in range(m, 2 * m + 1):
                if n_lookups * (domain // 128) > MAX_GATHERS:
                    continue  # outside the kernel's probed compile ceiling
                plan = _plan(m, n_lookups)
                rows = [f * m + k for k, f in plan]
                banks = []
                for shard, full in zip(shards, fulls):
                    stack = np.ascontiguousarray(full[rows])
                    banks.append(
                        FdrBank(
                            m=m,
                            domain=domain,
                            checks=plan,
                            tables=stack,
                            patterns=shard,
                            fp_per_byte=_fp_of_stack(stack),
                        )
                    )
                fp = sum(b.fp_per_byte for b in banks)
                cost = sum(b.scan_cost_ps() for b in banks)
                # prefer configurations within budget; among those, min
                # total cost; if none fits, min FP bounds the confirm
                key = (0, total_ps(cost, fp)) if fp <= fp_budget else (1, fp, cost)
                if best is None or key < best[0]:
                    best = (key, banks)
    assert best is not None
    return best[1]


def compile_fdr(
    patterns: list[str | bytes],
    *,
    ignore_case: bool = False,
    fp_budget_per_byte: float = FP_CEILING_PER_BYTE,
    max_banks: int = 4,
) -> FdrModel:
    """Compile a literal set (every literal >= 2 bytes) into filter banks.

    The window is set by the shortest member (suffix truncation makes every
    longer member representable in it).  When the set's lengths are mixed
    enough that splitting pays — a long-window group gets more slots and a
    short group stops poisoning it — the tuner compares every two-group
    split against the single-bank compile by total cost.  Raises FdrError
    for sets this filter cannot host (the engine routes those to the exact
    DFA-bank path instead)."""
    norm = _normalize(patterns, ignore_case)
    if not norm:
        raise FdrError("empty pattern set")
    if any(len(p) < 2 for p in norm):
        raise FdrError("FDR needs literals >= 2 bytes")

    def window_of(subset: list[bytes]) -> int:
        return min(MAX_DEPTHS + 1, min(len(p) for p in subset))

    def group_cost(banks: list[FdrBank]) -> float:
        scan = sum(b.scan_cost_ps() for b in banks)
        confirm = CONFIRM_PS_PER_CANDIDATE * sum(b.fp_per_byte for b in banks)
        return max(scan, confirm) + OVERLAP_RESIDUE * min(scan, confirm)

    candidates: list[list[FdrBank]] = []
    single = _compile_group(
        norm, window_of(norm) - 1, fp_budget_per_byte, max_banks
    )
    candidates.append(single)
    lengths = sorted({min(len(p), MAX_DEPTHS + 1) for p in norm})
    for t in lengths[1:]:
        short = [p for p in norm if min(len(p), MAX_DEPTHS + 1) < t]
        long_ = [p for p in norm if min(len(p), MAX_DEPTHS + 1) >= t]
        if len(short) < N_BUCKETS or len(long_) < N_BUCKETS:
            continue
        candidates.append(
            _compile_group(short, window_of(short) - 1, fp_budget_per_byte / 2, max_banks)
            + _compile_group(long_, window_of(long_) - 1, fp_budget_per_byte / 2, max_banks)
        )
    banks = min(candidates, key=group_cost)
    model = FdrModel(banks=banks, ignore_case=ignore_case, n_patterns=len(norm))
    if model.fp_per_byte > FP_CEILING_PER_BYTE:
        raise FdrError(
            f"set too dense to filter: best candidate rate "
            f"{model.fp_per_byte:.3g}/byte > {FP_CEILING_PER_BYTE:g}"
        )
    return model


# ------------------------------------------------------------------ reference

def reference_candidates(bank: FdrBank, data: bytes) -> np.ndarray:
    """NumPy oracle of the device filter for one bank: candidate end offsets
    (i+1 convention, like models/dfa.reference_scan) over a single stripe.

    Mirrors the kernel exactly, including the all-ones pipeline seed at the
    stripe start (conservative: early positions over-report rather than
    miss, and the engine confirms candidates exactly anyway).
    """
    arr = np.frombuffer(data, dtype=np.uint8).astype(np.int64)
    n = arr.size
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    prev = np.concatenate([[0], arr[:-1]])
    hashes = {
        f: pair_hash(prev, arr, bank.domain, which=f) for f in bank.families
    }
    ones = np.uint32(0xFFFFFFFF)
    slot_masks = np.full((bank.m, n), ones, dtype=np.uint32)
    for i, (slot, fam) in enumerate(bank.checks):
        slot_masks[slot] &= bank.tables[i][hashes[fam]]
    # pipeline: V_0(t) = masks[0, t]; V_k(t) = V_{k-1}(t-1) & masks[k, t]
    Vs = np.empty((bank.m, n), dtype=np.uint32)
    Vs[0] = slot_masks[0]
    for k in range(1, bank.m):
        shifted = np.concatenate([[ones], Vs[k - 1][:-1]])
        Vs[k] = shifted & slot_masks[k]
    return np.nonzero(Vs[bank.m - 1] != 0)[0].astype(np.int64) + 1


def reference_candidates_model(model: FdrModel, data: bytes) -> np.ndarray:
    """Union of per-bank candidate end offsets."""
    if model.ignore_case:
        data = bytes(data).lower()
    outs = [reference_candidates(b, data) for b in model.banks]
    return np.unique(np.concatenate(outs)) if outs else np.zeros(0, dtype=np.int64)


def exact_match_lines(patterns: list[bytes], data: bytes, ignore_case: bool) -> set[int]:
    """Simple oracle for tests: 1-based lines containing any literal."""
    hay = data.lower() if ignore_case else data
    needles = [p.lower() if ignore_case else p for p in patterns]
    out = set()
    for i, line in enumerate(hay.split(b"\n"), 1):
        if any(nd in line for nd in needles):
            out.add(i)
    return out

"""Exact short-literal-set scan model: the row-partition pair factorization.

Sets whose members are all 1-2 bytes are exactly the sets the FDR filter
cannot host (no pair window to hash ahead of, models/fdr.py "FDR needs
literals >= 2 bytes"), and until round 4 they routed to the native host
scanner (ops/engine.py) — the one pattern-set family with no device
engine.  This module gives them one, and it is EXACT on device (no host
confirm pass at all):

* The members form a 256x256 boolean matrix ``M[b0, b1]`` — True where
  the pair (b0, b1) is a 2-byte member; a 1-byte member {c} matches at
  any position whose byte is c regardless of the previous byte, so it
  folds in as the all-True column ``M[:, c] = True``.
* Partition the 256 ``b0`` rows by identical row pattern: ``rowcls[b0]``
  in [0, R).  Then ``M[b0, b1] == W[b1] >> rowcls[b0] & 1`` where
  ``W[b1]`` packs column b1's per-class bits into one uint32 — EXACT
  whenever R <= 32 (the common case: real short-pattern sets are built
  from ranges/digraph families with massive row duplication; a fully
  random dense set defeats it and keeps the native route).  When rows
  exceed 32 classes the transpose orientation (partition columns, index
  words by b0) is tried before giving up.

Per byte the kernel (ops/pallas_pairset.py) pays two 256-domain lane
lookups (rowcls of the previous byte, W of the current byte) = 4 gathers
+ ~3 VPU ops — the same gather economics as a 2-gather-check FDR plan
but with zero candidates to confirm.  The previous-byte carry is seeded
'\\n' at stripe starts: no member contains a newline, so a stripe head
can only UNDER-report (a 2-byte match spanning the boundary), which the
engine's boundary stitching restores — the same contract as every other
device engine here (never a false positive on an exact path).

Why not the MXU (VERDICT r3 item 7, closing the round-3 question): the
"shared 256-domain contraction" formulation — one-hot(byte) (L,256) @
class-membership (256,K) int8 — spends 256*K MACs per byte (K=32 class
columns -> 8192 MACs/byte, ~48 GB/s at v5e's full int8 peak) BEFORE
counting the one-hot build (a 256-way VPU compare sweep) and the
(L,256) cross-lane layout shuffles Mosaic must materialize.  Its ceiling
sits at/below the 4-gather VPU path's measured rate, so the gather
primitive wins even where the contraction genuinely is shared; measured
anchor in benchmarks/kernel_compare.py (mxu_dot vs pairset entries).

Reference: the workload is grep -f with short patterns
(/root/reference/application/grep.go:20-30 re-loops per line); the
factorization is original to this module.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

NL = 0x0A


class PairsetError(ValueError):
    pass


@dataclass(frozen=True)
class PairsetModel:
    """Exact device scan tables for a 1-2-byte literal set.

    ``transposed`` False: hit(t) = words[data[t]] >> rowcls[data[t-1]] & 1.
    ``transposed`` True:  hit(t) = words[data[t-1]] >> rowcls[data[t]] & 1.
    Either orientation reports the END offset (i+1 convention) of each
    match.
    """

    rowcls: np.ndarray  # (256,) uint32, values < 32
    words: np.ndarray  # (256,) uint32, bit per row/column class
    transposed: bool
    n_classes: int
    patterns: list[bytes]
    ignore_case: bool

    @property
    def window(self) -> int:
        return 2  # matches span <= 2 bytes: stripe-head misses are
        # confined to each stripe's first byte (engine boundary stitching)


def _normalize(patterns, ignore_case: bool) -> list[bytes]:
    out = []
    for p in patterns:
        b = p.encode("utf-8", "surrogateescape") if isinstance(p, str) else bytes(p)
        if not b:
            raise PairsetError("empty literal in pattern set")
        if NL in b:
            raise PairsetError("literal contains '\\n'")
        if len(b) > 2:
            raise PairsetError("pairset hosts only 1-2 byte literals")
        out.append(b.lower() if ignore_case else b)
    return out


def expected_match_density(patterns, *, ignore_case: bool = False) -> float:
    """Expected matches per scanned byte under the static byte-frequency
    prior (models/shift_and._byte_prior — English-prose letter frequencies
    with a uniform floor).

    The pairset kernel is EXACT, so its device words are matches, not
    candidates — but the host still pays O(matches) for the sparse
    coordinate fetch and per-line reporting.  A member like ``" "`` or
    ``"e"`` makes that ~0.1+ matches/byte: the device pass then buys
    nothing over the native host scanner while the offset fetch pays a
    device->host transfer the host path never needed.  The engine gates
    both pairset routes (pure-short mode and the mixed-set 1-byte
    sidecar) on this estimate against models/fdr.FP_CEILING_PER_BYTE —
    the same ceiling that keeps over-dense sets off the FDR filter.
    The estimate is the MAX over two corpus models — the uniform-floored
    prior (binary corpora) and the prose-conditional `_text_prior` (text,
    where ' ' really is ~15% of bytes) — so a dense member is caught
    under whichever model makes it dense.  Like the shift-and rare-class
    prior, a corpus can still defeat the estimate; that affects only
    throughput, never exactness."""
    from distributed_grep_tpu.models.shift_and import _byte_prior, _text_prior

    norm = _normalize(patterns, ignore_case)
    M = np.zeros((256, 256), dtype=np.float64)
    for p in norm:
        if len(p) == 2:
            M[p[0], p[1]] = 1.0
        else:  # 1-byte member: any previous byte
            M[:, p[0]] = 1.0
    dens = 0.0
    for q in (_byte_prior(), _text_prior()):
        q = np.asarray(q, dtype=np.float64).copy()
        if ignore_case:
            # members are stored folded and the kernel folds corpus bytes:
            # a lowercase byte's effective frequency absorbs its uppercase
            for c in range(ord("a"), ord("z") + 1):
                q[c] += q[c - 32]
                q[c - 32] = 0.0
        dens = max(dens, float(q @ M @ q))
    return dens


def _factorize(M: np.ndarray) -> tuple[np.ndarray, np.ndarray, int] | None:
    """Partition the 256 rows of a (256, 256) bool matrix by identical
    pattern; return (rowcls, words, n_classes) or None if > 32 classes."""
    view = np.ascontiguousarray(M).view(
        np.dtype((np.void, M.shape[1] * M.dtype.itemsize))
    ).ravel()
    _, first_idx, inverse = np.unique(view, return_index=True, return_inverse=True)
    n_cls = len(first_idx)
    if n_cls > 32:
        return None
    # stable class ids: order classes by their first-occurring row
    sorted_first = np.sort(first_idx)
    remap = np.zeros(n_cls, dtype=np.uint32)
    for new_r, i in enumerate(sorted_first):
        remap[inverse[i]] = new_r
    rowcls = remap[inverse].astype(np.uint32)
    words = np.zeros(256, dtype=np.uint32)
    for new_r, i in enumerate(sorted_first):
        cols = np.nonzero(M[i])[0]
        words[cols] |= np.uint32(1) << np.uint32(new_r)
    return rowcls, words, n_cls


def compile_pairset(patterns, *, ignore_case: bool = False) -> PairsetModel:
    """Compile a 1-2-byte literal set; raises PairsetError when the set is
    not exactly representable (row AND column partitions both > 32
    classes — the fully-random-dense corner, which keeps the native host
    route)."""
    norm = _normalize(patterns, ignore_case)
    if not norm:
        raise PairsetError("empty pattern set")
    M = np.zeros((256, 256), dtype=bool)
    for p in norm:
        if len(p) == 2:
            M[p[0], p[1]] = True
        else:  # 1-byte member: matches whatever the previous byte was
            M[:, p[0]] = True

    fact = _factorize(M)
    if fact is not None:
        rowcls, words, n_cls = fact
        return PairsetModel(
            rowcls=rowcls, words=words, transposed=False,
            n_classes=max(n_cls, 1), patterns=norm, ignore_case=ignore_case,
        )
    fact_t = _factorize(np.ascontiguousarray(M.T))
    if fact_t is not None:
        colcls, words_t, n_cls = fact_t
        return PairsetModel(
            rowcls=colcls, words=words_t, transposed=True,
            n_classes=max(n_cls, 1), patterns=norm, ignore_case=ignore_case,
        )
    raise PairsetError(
        "pair matrix needs > 32 row and column classes — not exactly "
        "representable; set keeps the native host route"
    )


# ------------------------------------------------------------------ reference

def reference_ends(model: PairsetModel, data: bytes) -> np.ndarray:
    """NumPy oracle: EXACT end offsets (i+1) of all matches in one stripe,
    mirroring the kernel including its prev='\\n' seed at the stripe
    start (a 2-byte match whose first byte precedes the stripe is missed
    there — under-report only; the engine's boundary stitching restores
    it)."""
    arr = np.frombuffer(data, dtype=np.uint8).astype(np.int64)
    if model.ignore_case:
        arr = np.where((arr >= 65) & (arr <= 90), arr + 32, arr)
    if arr.size == 0:
        return np.zeros(0, dtype=np.int64)
    prev = np.concatenate([[NL], arr[:-1]])
    if model.transposed:
        hit = (model.words[prev] >> model.rowcls[arr]) & 1
    else:
        hit = (model.words[arr] >> model.rowcls[prev]) & 1
    return np.nonzero(hit)[0].astype(np.int64) + 1


def exact_match_lines(model: PairsetModel, data: bytes) -> set[int]:
    """Line-level oracle for tests (independent of the kernel seed)."""
    hay = data.lower() if model.ignore_case else data
    out = set()
    for i, line in enumerate(hay.split(b"\n"), 1):
        if any(p in line for p in model.patterns):
            out.add(i)
    return out

"""Approximate (edit-distance <= k) matching — the agrep model family.

The reference's grep (application/grep.go) is exact-only; approximate
matching is the classic extension (agrep / Wu-Manber, "Fast text searching
allowing errors", CACM 1992) and its bit-parallel formulation is a natural
fit for the same TPU VPU scan the shift-and engine uses: the automaton
state becomes k+1 uint32 rows per lane, one per error budget, and a byte
step is pure shift/and/or arithmetic on those rows — no gathers.

Recurrence (per byte c, rows R_0..R_k, B from the shift-and model):

    R_0' = ((R_0 << 1) | 1) & B[c]
    R_j' = (((R_j << 1) | 1) & B[c])      exact extension
         | R_{j-1}                        insertion  (text char inserted)
         | (R_{j-1} << 1)                 substitution
         | (R'_{j-1} << 1)                deletion   (pattern char skipped)
         | ((1 << j) - 1)                 seed: bits < j are always live
                                          (prefix p[0..i] reaches any text
                                          position within i+1 <= j edits)

Bit i of R_j = "pattern prefix p[0..i] matches a suffix of the text read
so far with <= j errors"; a match ends wherever bit m-1 of R_k is set.

Line semantics: grep matches within lines, so every '\n' resets the rows
to their line-start seeds R_j = (1<<j)-1 *before* the match check — an
errorful match can never span or consume a newline.  Patterns with length <= k degenerate to "every line matches"
(delete the whole pattern); the engine short-circuits that case exactly
like an empty-regex pattern.

Eligibility: any shift-and-eligible pattern (literal / class sequence,
<= 32 symbols) with 1 <= k < length, k <= MAX_ERRORS.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from distributed_grep_tpu.models.shift_and import ShiftAndModel, try_compile_shift_and

NL = 0x0A
MAX_ERRORS = 3  # k+1 state rows per lane; beyond this the DFA product blows up


@dataclass
class ApproxModel:
    """Shift-and B-masks plus an error budget."""

    base: ShiftAndModel
    k: int

    @property
    def length(self) -> int:
        return self.base.length

    @property
    def match_bit(self) -> np.uint32:
        return self.base.match_bit

    @property
    def seeds(self) -> list[int]:
        """Line-start row seeds: R_j starts with j leading deletions."""
        return [(1 << j) - 1 for j in range(self.k + 1)]


def try_compile_approx(
    pattern: str, k: int, ignore_case: bool = False
) -> ApproxModel | None:
    """Compile if `pattern` is shift-and-eligible and 1 <= k < length."""
    if not 1 <= k <= MAX_ERRORS:
        return None
    base = try_compile_shift_and(pattern, ignore_case=ignore_case)
    if base is None or base.length <= k:
        return None
    return ApproxModel(base=base, k=k)


def scan_reference(model: ApproxModel, data: bytes) -> np.ndarray:
    """Host oracle: match end offsets (i+1 convention), one stripe.

    Python-int implementation of the exact kernel recurrence — used for
    boundary-line re-scans and as the test reference.
    """
    b_table = model.base.b_table
    mb = int(model.match_bit)
    k = model.k
    seeds = model.seeds
    R = list(seeds)
    out = []
    for i, c in enumerate(data):
        if c == NL:
            R = list(seeds)
        else:
            b = int(b_table[c])
            prev = R
            new = [((prev[0] << 1) | 1) & b]
            for j in range(1, k + 1):
                new.append(
                    ((((prev[j] << 1) | 1) & b)
                     | prev[j - 1]
                     | (prev[j - 1] << 1)
                     | (new[j - 1] << 1)
                     | seeds[j]) & 0xFFFFFFFF
                )
            R = new
        if R[k] & mb:
            out.append(i + 1)
    return np.asarray(out, dtype=np.int64)


def line_matches(model: ApproxModel, line: bytes) -> bool:
    """Does this (newline-free) line contain a <= k-error match?"""
    return scan_reference(model, line).size > 0


def dp_oracle_line(pattern_syms: list[list[tuple[int, int]]], line: bytes, k: int) -> bool:
    """Independent O(n*m) edit-distance-substring oracle for tests: does
    some substring of `line` match the symbol sequence within k edits?
    Symbols are the shift-and (lo, hi) range lists."""
    m = len(pattern_syms)
    prev = list(range(m + 1))  # D[0][j] = j (deletions); free start in text
    best = prev[m]
    for c in line:
        cur = [0] * (m + 1)  # free start: D[i][0] = 0
        for j in range(1, m + 1):
            hit = any(lo <= c <= hi for lo, hi in pattern_syms[j - 1])
            cur[j] = min(
                prev[j - 1] + (0 if hit else 1),  # match / substitution
                prev[j] + 1,  # insertion (extra text char)
                cur[j - 1] + 1,  # deletion (skip pattern char)
            )
        best = min(best, cur[m])
        prev = cur
    return best <= k

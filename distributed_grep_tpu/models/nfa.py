"""Glushkov position automaton -> bit-parallel NFA model for the Pallas path.

The DFA engine (models/dfa.py) is exact for the whole grep -E subset but its
device scan needs one table gather per byte — and TPU has no vector gather,
so the XLA fallback runs the gather through lax.scan at ~0.1 GB/s (measured,
benchmarks/kernel_compare.py).  The shift-and kernel avoids gathers entirely
(state = bits, B[byte] = range compares) but only covers plain symbol
sequences <= 32 symbols.

This model closes the gap for general regex: the Glushkov (position)
automaton of the pattern, simulated bit-parallel.  One bit per *position*
(= char edge of the Thompson NFA, models/dfa._Nfa); a byte step is

    D' = (follow(D) | init) & B[byte]

where follow(D) = OR of follow[p] over set bits p, init re-activates the
pattern starts (the unanchored Sigma* restart, plus '^' starts only after a
newline), and B[byte] has bit p set iff the byte is in position p's class.
All of it is VPU bit-ops + compares — gather-free, so it runs on the same
286 GB/s Pallas path as shift-and (ops/pallas_nfa.py).

The kernel plan exploits that most positions in real patterns sit in plain
concatenation runs where follow[p] == {p+1}: all such "chain" bits advance
with ONE masked shift per state word, exactly like shift-and.  Only branch
points (alternation heads/tails, repeat back-edges, word-boundary bits) pay
an individual select.  An 8-word alternation therefore costs barely more
than a literal scan.

Eligibility (try_compile_glushkov returns None otherwise; caller falls back
to the DFA/XLA path): <= MAX_POSITIONS positions after bounded-repeat
expansion, no '$' accepts (they need next-byte lookahead, which would
misattribute the match to the newline's line in the packed-bit convention —
dfa.py's accept_eol plane handles them), pattern not nullable (empty-match
patterns match every line; the engine short-circuits those before any scan).

Reference behaviour cross-check: compile_dfa on the same pattern is the
oracle (tests/test_nfa.py) — the two compilers share the parser and the
Thompson construction (dfa.py:106-403), so semantic drift is structural,
not incidental.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from distributed_grep_tpu.models import dfa as _dfa
from distributed_grep_tpu.models.dfa import NL, RegexError

# State spans MAX_POSITIONS/32 uint32 words per lane.  128 (4 words) since
# the kernel's gather-B mode made wide patterns affordable — per-word B
# cost is fixed (ops/pallas_nfa.use_gather_b) — and pallas_nfa.MAX_COST
# still gates genuinely expensive automata onto the XLA DFA path.
MAX_POSITIONS = 128
WORD_BITS = 32


@dataclass
class GlushkovModel:
    """Bit-parallel position-automaton tables + the Pallas kernel plan.

    n_pos       number of Glushkov positions (char edges)
    sym_masks   per position, 256-bit byte-membership mask
    follow      per position, n_pos-bit mask of successor positions
    init_float  positions active at every byte (unanchored restart)
    init_anchor positions active only at line starts ('^' branches),
                *minus* init_float
    final       positions whose activation means "a match ends here"
    """

    n_pos: int
    sym_masks: list[int]
    follow: list[int]
    init_float: int
    init_anchor: int
    final: int
    pattern: str

    # ---- kernel plan (derived in __post_init__) --------------------------
    # classes: positions grouped by identical byte set; per class the byte
    # set as (lo, hi) ranges and the per-word position masks it contributes
    # to B.  chain_src: per word, bits p with follow[p] == {p+1} in-word.
    # specials: (word, bit, ((word, mask), ...)) per remaining position.
    def __post_init__(self) -> None:
        self.n_words = (self.n_pos + WORD_BITS - 1) // WORD_BITS
        cls_of: dict[int, list[int]] = {}
        for p, m in enumerate(self.sym_masks):
            cls_of.setdefault(m, []).append(p)
        self.cls_ranges: list[tuple[tuple[int, int], ...]] = []
        self.cls_pos_words: list[tuple[tuple[int, int], ...]] = []
        for mask, ps in cls_of.items():
            self.cls_ranges.append(tuple(_mask_to_ranges(mask)))
            self.cls_pos_words.append(tuple(_bits_to_words(ps, self.n_words)))
        chain = [0] * self.n_words
        specials: list[tuple[int, int, tuple[tuple[int, int], ...]]] = []
        for p, f in enumerate(self.follow):
            if f == 0:
                continue
            if f == (1 << (p + 1)) and (p % WORD_BITS) != WORD_BITS - 1:
                chain[p // WORD_BITS] |= 1 << (p % WORD_BITS)
            else:
                words = _int_to_words(f, self.n_words)
                specials.append(
                    (p // WORD_BITS, p % WORD_BITS,
                     tuple((w, m) for w, m in enumerate(words) if m))
                )
        self.chain_src = tuple(chain)
        self.specials = tuple(specials)
        self.init_float_words = tuple(_int_to_words(self.init_float, self.n_words))
        self.init_anchor_words = tuple(_int_to_words(self.init_anchor, self.n_words))
        self.final_words = tuple(_int_to_words(self.final, self.n_words))

    @property
    def total_ranges(self) -> int:
        return sum(len(r) for r in self.cls_ranges)

    @property
    def n_classes(self) -> int:
        return len(self.cls_ranges)

    @property
    def n_specials(self) -> int:
        return len(self.specials)

    def kernel_plan(self) -> tuple:
        """Hashable plan consumed by ops/pallas_nfa (static jit arg)."""
        return (
            self.n_words,
            tuple(zip(self.cls_ranges, self.cls_pos_words)),
            self.chain_src,
            self.specials,
            self.init_float_words,
            self.init_anchor_words,
            self.final_words,
            bool(self.init_anchor),
        )


def _mask_to_ranges(mask: int) -> list[tuple[int, int]]:
    ranges: list[tuple[int, int]] = []
    b = 0
    while b < 256:
        if mask >> b & 1:
            lo = b
            while b < 256 and mask >> b & 1:
                b += 1
            ranges.append((lo, b - 1))
        else:
            b += 1
    return ranges


def _int_to_words(v: int, n_words: int) -> list[int]:
    return [(v >> (WORD_BITS * w)) & 0xFFFFFFFF for w in range(n_words)]


def _bits_to_words(bits: list[int], n_words: int) -> list[tuple[int, int]]:
    words = [0] * n_words
    for p in bits:
        words[p // WORD_BITS] |= 1 << (p % WORD_BITS)
    return [(w, m) for w, m in enumerate(words) if m]


def _relax_bounded(node) -> tuple[object, bool]:
    """Copy of the AST with every bounded repeat {m,n} (finite n > m)
    widened to {m,} — a language SUPERSET whose Glushkov automaton spends
    min+1 copies of the body instead of n.  The relaxed automaton is only
    usable as a candidate FILTER: every exact match is also a relaxed
    match at the same end offset, so candidate lines are a superset and a
    host confirm of each candidate line restores exactness (the same
    filter+confirm architecture the shift-and rare-class and FDR paths
    use).  Returns (node, changed)."""
    if isinstance(node, _dfa.Repeat):
        inner, ch = _relax_bounded(node.node)
        if node.max is not None and node.max > node.min:
            return _dfa.Repeat(inner, node.min, None), True
        return (_dfa.Repeat(inner, node.min, node.max), True) if ch else (node, False)
    if isinstance(node, _dfa.Concat):
        parts = [_relax_bounded(p) for p in node.parts]
        if any(c for _, c in parts):
            return _dfa.Concat([p for p, _ in parts]), True
        return node, False
    if isinstance(node, _dfa.Alt):
        opts = [_relax_bounded(o) for o in node.options]
        if any(c for _, c in opts):
            return _dfa.Alt([o for o, _ in opts]), True
        return node, False
    return node, False


def try_compile_glushkov(
    pattern: str, ignore_case: bool = False, max_positions: int = MAX_POSITIONS
) -> GlushkovModel | None:
    """Compile to a bit-parallel position automaton, or None if ineligible.

    Reuses dfa.py's parser, anchor splitting, and Thompson construction so
    the supported syntax and line semantics are identical to compile_dfa;
    RegexError propagates (the caller's compile_dfa will surface it)."""
    ast = _dfa._Parser(pattern, ignore_case).parse()
    return _compile_from_ast(ast, pattern, max_positions)


def compile_scan_model(
    pattern: str, ignore_case: bool = False, max_positions: int = MAX_POSITIONS
) -> tuple[GlushkovModel | None, bool]:
    """(model, is_filter) — the automaton the device scan should run.

    Exact when that is also the cheapest; when relaxing bounded repeats
    saves state WORDS (the kernel's per-byte cost is linear in words —
    config 4's `{4,24}` is 33 positions = 2 words exact, 14 = 1 word
    relaxed), or when only the relaxed form fits the position cap at all,
    returns the filter model with is_filter=True: its match offsets are a
    candidate superset and the engine must confirm candidate lines on
    host (ops/engine.py `cand_words`)."""
    ast = _dfa._Parser(pattern, ignore_case).parse()
    exact = _compile_from_ast(ast, pattern, max_positions)
    relaxed_ast, changed = _relax_bounded(ast)
    if not changed:
        return exact, False
    filt = _compile_from_ast(relaxed_ast, pattern, max_positions)
    if filt is None or (exact is not None and filt.n_words >= exact.n_words):
        return exact, False
    return filt, True


def _count_positions(node) -> int:
    """Char positions the Glushkov/Thompson construction will spend on
    `node` (char edges, counting repeat expansion the way _Nfa._build_repeat
    does: min copies plus one loop copy for unbounded, max copies bounded)."""
    if isinstance(node, _dfa.Char):
        return 1
    if isinstance(node, _dfa.Concat):
        return sum(_count_positions(p) for p in node.parts)
    if isinstance(node, _dfa.Alt):
        return sum(_count_positions(o) for o in node.options)
    if isinstance(node, _dfa.Repeat):
        inner = _count_positions(node.node)
        copies = node.min + (1 if node.max is None else node.max - node.min)
        return inner * max(copies, 1)
    return 0  # Anchor: no char positions


def _truncate_prefix(node, budget: int):
    """Longest REQUIRED prefix of `node` fitting `budget` positions, or
    None if no usable prefix exists.  Only prefixes every match must
    contain are kept — optional parts (min-0 repeats) and alternations
    never get partially included — so any string matching `node` has a
    substring matching the truncation: a candidate FILTER at line
    granularity (see compile_device_filter)."""
    if _count_positions(node) <= budget:
        return node
    if isinstance(node, _dfa.Concat):
        kept, used = [], 0
        for part in node.parts:
            c = _count_positions(part)
            if used + c <= budget:
                kept.append(part)
                used += c
                continue
            t = _truncate_prefix(part, budget - used)
            if t is not None:
                kept.append(t)
            break  # everything after the cut is dropped
        if not kept:
            return None
        return kept[0] if len(kept) == 1 else _dfa.Concat(kept)
    if isinstance(node, _dfa.Repeat) and node.min >= 1:
        # the first min copies are required: keep k <= min whole copies
        inner = _count_positions(node.node)
        k = min(budget // inner, node.min) if inner else 0
        if k < 1:
            return None
        return _dfa.Repeat(node.node, k, k)
    return None  # Alt / optional repeat / single big leaf: no required prefix


def compile_device_filter(
    pattern: str, ignore_case: bool = False, max_positions: int = MAX_POSITIONS
) -> GlushkovModel | None:
    """A Glushkov FILTER for single patterns outside the exact device
    kernel subset: '$' end-anchors dropped, bounded repeats relaxed, and
    over-cap bodies truncated to a required prefix.

    Every transform yields a language superset at LINE granularity — a
    line containing an exact match always contains a filter match ('$'
    removal keeps the same end offsets; prefix truncation keeps a
    required substring) — so the engine's existing cand_words host-confirm
    contract (ops/engine.py, per-line DFA re-check) restores exactness,
    the same architecture as the relaxed-repeat filter above.  This is
    what puts everyday patterns like ``error$`` and >MAX_POSITIONS
    literals on the Pallas path instead of the host scanner (reference
    analogue: application/grep.go:21 — regexp.Match handles '$' on the
    worker; the TPU path must too).

    Returns None when no non-nullable filter compiles (caller keeps the
    host route)."""
    try:
        ast = _dfa._Parser(pattern, ignore_case).parse()
    except RegexError:
        return None
    relaxed, _ = _relax_bounded(ast)
    # Mid-pattern anchors strip to epsilon (language superset, same end
    # offsets — see _strip_anchors): '(^a|b)c' filters as '(a|b)c', and
    # the per-line host confirm re-applies the real assertions.  Without
    # this the Glushkov builder rejects anchored bodies outright
    # (_has_anchor) and such patterns would stay off the device.
    branches = [
        (a_start, _strip_anchors(body))
        for a_start, body, _ in _dfa._split_anchors(relaxed)
    ]
    total = sum(_count_positions(b) for _, b in branches)
    # Fits untruncated: keep the whole body (max selectivity — the filter
    # then differs from the pattern only by the dropped '$').  Over cap:
    # prefer a 32-position truncation (1 state word — the fastest kernel
    # shape; a 32-symbol required prefix is already astronomically
    # selective) and widen to the full cap only if 32 yields no usable
    # prefix (e.g. leading optional parts making short prefixes nullable).
    if total <= max_positions:
        whole = [(a_start, body, False) for a_start, body in branches]
        try:
            return _compile_from_branches(whole, pattern, max_positions)
        except RegexError:
            return None
    for budget in (32, max_positions):
        per = max(1, budget // max(len(branches), 1))
        trunc = []
        for a_start, body in branches:
            t = _truncate_prefix(body, per)
            if t is None:
                trunc = None
                break
            trunc.append((a_start, t, False))
        if trunc is None:
            continue
        try:
            m = _compile_from_branches(trunc, pattern, max_positions)
        except RegexError:
            return None
        if m is not None:
            return m
    return None


def _compile_from_ast(
    ast, pattern: str, max_positions: int
) -> GlushkovModel | None:
    branches = _dfa._split_anchors(ast)
    if any(a_end for _, _, a_end in branches):
        return None  # '$' needs next-byte lookahead — DFA path handles it
    return _compile_from_branches(branches, pattern, max_positions)


def _has_anchor(node) -> bool:
    """True when `node` contains an Anchor anywhere (mid-pattern '^'/'$'
    — _split_anchors only pops top-level ones).  The DFA's subset
    construction represents these exactly via ls_eps/eol_eps edges
    (models/dfa.py, round 5), but this bit-parallel position automaton
    has no position-gated epsilon: its closure would silently treat the
    anchored continuation as dead — an UNDER-approximation that is wrong
    for the exact automaton and fatal for a filter (filters must only
    over-approximate).  Such bodies are rejected here; the device filter
    path strips the anchors instead (_strip_anchors — a superset)."""
    if isinstance(node, _dfa.Anchor):
        return True
    if isinstance(node, _dfa.Concat):
        return any(_has_anchor(p) for p in node.parts)
    if isinstance(node, _dfa.Alt):
        return any(_has_anchor(o) for o in node.options)
    if isinstance(node, _dfa.Repeat):
        return _has_anchor(node.node)
    return False


def _strip_anchors(node):
    """Copy of the AST with every Anchor replaced by epsilon (an empty
    Concat).  Anchors consume nothing, so removal keeps every exact
    match's end offset while enlarging the language — a candidate FILTER
    transform with the same contract as dropping a trailing '$'."""
    if isinstance(node, _dfa.Anchor):
        return _dfa.Concat([])
    if isinstance(node, _dfa.Concat):
        parts = [_strip_anchors(p) for p in node.parts]
        parts = [p for p in parts if not (isinstance(p, _dfa.Concat) and not p.parts)]
        return _dfa.Concat(parts)
    if isinstance(node, _dfa.Alt):
        return _dfa.Alt([_strip_anchors(o) for o in node.options])
    if isinstance(node, _dfa.Repeat):
        return _dfa.Repeat(_strip_anchors(node.node), node.min, node.max)
    return node


def _compile_from_branches(
    branches, pattern: str, max_positions: int
) -> GlushkovModel | None:
    if any(_has_anchor(body) for _, body, *_ in branches):
        return None  # mid-pattern anchors: DFA/native exact paths only
    nfa = _dfa._Nfa()
    root = nfa.new_state()  # line-start entry
    floating = nfa.new_state()  # unanchored restart entry (no self-loop edge:
    nfa.states[root].eps.append(floating)  # the kernel re-injects init_float
    accepts: set[int] = set()  # at every byte instead)
    try:
        for a_start, body, _ in branches:
            s, a = nfa.build(body)
            (nfa.states[root] if a_start else nfa.states[floating]).eps.append(s)
            accepts.add(a)
    except _dfa.TooManyStates:
        return None  # bounded-repeat expansion blew the cap -> DFA/host path

    # positions = char edges, in (state, edge) order
    positions: list[tuple[int, int, int]] = []  # (source, mask, target)
    for sid, st in enumerate(nfa.states):
        for mask, tgt in st.chars:
            positions.append((sid, mask, tgt))
    n_pos = len(positions)
    if n_pos == 0 or n_pos > max_positions:
        return None

    def closure(seed: frozenset[int]) -> frozenset[int]:
        stack, seen = list(seed), set(seed)
        while stack:
            s = stack.pop()
            for t in nfa.states[s].eps:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    pos_of_source: dict[int, int] = {}
    for i, (src, _, _) in enumerate(positions):
        pos_of_source.setdefault(src, 0)
        pos_of_source[src] |= 1 << i

    def pos_from(states: frozenset[int]) -> int:
        m = 0
        for s in states:
            m |= pos_of_source.get(s, 0)
        return m

    root_cl = closure(frozenset({root}))
    if root_cl & accepts:
        return None  # nullable: empty match — engine short-circuits pre-scan
    float_cl = closure(frozenset({floating}))
    init_line = pos_from(root_cl)
    init_float = pos_from(float_cl)

    follow: list[int] = []
    final = 0
    for i, (_, _, tgt) in enumerate(positions):
        tcl = closure(frozenset({tgt}))
        follow.append(pos_from(tcl))
        if tcl & accepts:
            final |= 1 << i

    return GlushkovModel(
        n_pos=n_pos,
        sym_masks=[m for _, m, _ in positions],
        follow=follow,
        init_float=init_float,
        init_anchor=init_line & ~init_float,
        final=final,
        pattern=pattern,
    )


def scan_reference(model: GlushkovModel, data: bytes) -> np.ndarray:
    """Host-side oracle: end offsets (index+1) of every match (line-start
    state at offset 0, newline resets — the device scan's exact semantics)."""
    b_table = [0] * 256
    for cls_ranges, pos_words in zip(model.cls_ranges, model.cls_pos_words):
        mask = 0
        for w, m in pos_words:
            mask |= m << (WORD_BITS * w)
        for lo, hi in cls_ranges:
            for byte in range(lo, hi + 1):
                b_table[byte] |= mask
    d = 0
    prev_nl = True
    hits = []
    for i, byte in enumerate(data):
        reached = model.init_float | (model.init_anchor if prev_nl else 0)
        dd = d
        while dd:
            p = (dd & -dd).bit_length() - 1
            reached |= model.follow[p]
            dd &= dd - 1
        d = reached & b_table[byte]
        if d & model.final:
            hits.append(i + 1)
        prev_nl = byte == NL
    return np.asarray(hits, dtype=np.uint64)

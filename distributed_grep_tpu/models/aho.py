"""Aho-Corasick multi-pattern automaton, emitted as a DFA scan table.

Multi-literal pattern sets (grep -f / Hyperscan-style rule sets,
BASELINE.json configs 3 and 5) compile to a trie with failure links,
resolved into the same dense ``DfaTable`` the single-pattern engine uses —
so the TPU byte-scan kernel is identical; only the host-side compiler
differs.  Accept states answer "some pattern ends at this byte", which is
exactly grep's per-line match semantics.

Construction is the textbook algorithm: build the trie, BFS to compute
failure links, then densify goto+failure into full transitions; finally
compress byte columns into equivalence classes and force the newline-reset
column like compile_dfa does.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from distributed_grep_tpu.models.dfa import NL, DfaTable, RegexError, TooManyStates


def compile_aho_corasick(
    patterns: list[str | bytes],
    ignore_case: bool = False,
    max_states: int = 1 << 16,
) -> DfaTable:
    """Compile a literal pattern set into a newline-reset DfaTable."""
    if not patterns:
        raise RegexError("empty pattern set")
    needles: list[bytes] = []
    for p in patterns:
        b = p.encode("utf-8", "surrogateescape") if isinstance(p, str) else bytes(p)
        if not b:
            raise RegexError("empty literal in pattern set")
        if NL in b:
            raise RegexError("literal contains '\\n' — not representable per-line")
        needles.append(b.lower() if ignore_case else b)

    # --- trie --------------------------------------------------------------
    goto: list[dict[int, int]] = [{}]
    accept_sets: list[bool] = [False]

    def add(word: bytes) -> None:
        s = 0
        for byte in word:
            if byte not in goto[s]:
                if len(goto) >= max_states:
                    raise TooManyStates(f"pattern set needs >{max_states} trie states")
                goto[s][byte] = len(goto)
                goto.append({})
                accept_sets.append(False)
            s = goto[s][byte]
        accept_sets[s] = True

    for w in needles:
        add(w)
    n = len(goto)

    # --- failure links (BFS) ----------------------------------------------
    fail = [0] * n
    q: deque[int] = deque()
    for byte, s in goto[0].items():
        q.append(s)
    while q:
        u = q.popleft()
        accept_sets[u] = accept_sets[u] or accept_sets[fail[u]]
        for byte, v in goto[u].items():
            q.append(v)
            f = fail[u]
            while f and byte not in goto[f]:
                f = fail[f]
            fail[v] = goto[f].get(byte, 0) if goto[f].get(byte, 0) != v else 0

    # --- densify to full transitions --------------------------------------
    # delta[s][b] = goto with failure resolution; column '\n' forced to 0.
    full = np.zeros((n, 256), dtype=np.uint16)
    order = list(range(n))  # BFS order from construction: parents precede children
    # Recompute in BFS order so delta[fail[u]] is ready before delta[u].
    bfs = [0]
    q = deque(goto[0].values())
    while q:
        u = q.popleft()
        bfs.append(u)
        q.extend(goto[u].values())
    for s in bfs:
        for b in range(256):
            if b == NL:
                full[s, b] = 0
                continue
            if ignore_case and ord("A") <= b <= ord("Z"):
                lookup = b + 32
            else:
                lookup = b
            if lookup in goto[s]:
                full[s, b] = goto[s][lookup]
            else:
                full[s, b] = 0 if s == 0 else full[fail[s], b]

    # --- byte-class compression -------------------------------------------
    cols, byte_to_cls = np.unique(full, axis=1, return_inverse=True)
    # keep '\n' in its own class even if its column collides with another
    nl_cls = int(byte_to_cls[NL])
    if int(np.sum(byte_to_cls == nl_cls)) > 1:
        byte_to_cls = byte_to_cls.copy()
        byte_to_cls[NL] = cols.shape[1]
        cols = np.concatenate([cols, np.zeros((n, 1), dtype=cols.dtype)], axis=1)
    trans = np.ascontiguousarray(cols, dtype=np.uint16)

    # Full-alphabet binary rulesets reach 256 classes (the forced-NL column
    # only ever replaces a shared one, so 257 is unreachable); uint16 keeps
    # headroom anyway and matches the int32 cast the device scan applies.
    return DfaTable(
        trans=trans,
        byte_to_cls=byte_to_cls.astype(np.uint16),
        accept=np.asarray(accept_sets, dtype=bool),
        accept_eol=np.zeros(n, dtype=bool),
        start=0,
        pattern=f"<aho-corasick {len(needles)} literals>",
    )


def compile_aho_corasick_banks(
    patterns: list[str | bytes],
    ignore_case: bool = False,
    max_states_per_bank: int = 1 << 16,
) -> list[DfaTable]:
    """Compile an arbitrarily large literal set into one or more DfaTables.

    Hyperscan-scale rulesets (10k+ patterns, BASELINE.json config 5) exceed
    the uint16 state space of a single automaton; the Hyperscan-style answer
    is to shard the ruleset into independent banks and scan each — on TPU the
    banks are extra lane-parallel passes over the same device-resident bytes,
    and grep's per-line semantics make the union of per-bank matched lines
    exact.  Patterns are greedily packed by worst-case trie size (one state
    per byte) so each bank compiles within its state budget.
    """
    norm: list[bytes] = [
        p.encode("utf-8", "surrogateescape") if isinstance(p, str) else bytes(p) for p in patterns
    ]
    if not norm:
        raise RegexError("empty pattern set")
    banks: list[list[bytes]] = []
    cur: list[bytes] = []
    cur_states = 1  # root
    for p in norm:
        cost = len(p)
        if cur and cur_states + cost > max_states_per_bank - 1:
            banks.append(cur)
            cur, cur_states = [], 1
        cur.append(p)
        cur_states += cost
    if cur:
        banks.append(cur)
    return [
        compile_aho_corasick(b, ignore_case=ignore_case, max_states=max_states_per_bank)
        for b in banks
    ]

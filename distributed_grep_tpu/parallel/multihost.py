"""Multi-host glue: jax.distributed + the coordinator protocol over DCN.

Topology (mirrors the reference's shape — a coordinator host + worker
hosts, SURVEY.md §5 distributed-backend mapping):

* Control plane: the four-verb HTTP protocol (runtime/http_coordinator.py)
  runs over DCN exactly as the reference's net/rpc ran over the LAN.  One
  worker process per host asks for splits and commits results.
* Compute plane: each worker process drives all chips local to its host
  through parallel/sharded_scan over a mesh of its local devices.
* For jobs that want one global mesh spanning hosts (a full pod slice),
  `init_distributed` wires jax.distributed so jax.devices() is global and
  meshes may span hosts; collectives then ride ICI within a slice and DCN
  across slices — standard JAX SPMD.  The MapReduce layer is agnostic:
  a "worker" is whoever called AssignTask, whether it owns 1 chip or a
  4x4 slice.  The segment feed honors the multi-process contract: when
  process_count > 1 each process materializes only its local lane blocks
  and assembles the global array from single-device shards
  (parallel/sharded_kernels._put_spec) — device_put of a full host array
  onto a cross-host mesh would try to address remote chips.
"""

from __future__ import annotations

import os

from distributed_grep_tpu.utils.logging import get_logger

log = get_logger("multihost")


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize jax.distributed from args or standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID);
    explicit args win over env.  Returns True if distributed mode was
    initialized, False for single-process operation (the common
    single-host case).  jax is imported only when an address is
    configured, so CPU-only workers never pay the import."""
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if addr is None:
        return False
    kwargs = {}
    n = num_processes if num_processes is not None else os.environ.get("JAX_NUM_PROCESSES")
    pid = process_id if process_id is not None else os.environ.get("JAX_PROCESS_ID")
    if n is not None:
        kwargs["num_processes"] = int(n)
    if pid is not None:
        kwargs["process_id"] = int(pid)
    import jax

    jax.distributed.initialize(coordinator_address=addr, **kwargs)
    log.info(
        "jax.distributed initialized: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )
    return True


def local_mesh_devices() -> list:
    """Devices this process should put in its worker-local mesh."""
    import jax

    return jax.local_devices()

"""Device-mesh fan-out: the TPU analogue of the reference's 2-Pi cluster.

The reference scales by fanning file-grained tasks over worker processes on
separate hosts (SURVEY.md §2 parallelism checklist).  Here the same data
parallelism rides a jax.sharding.Mesh:

* ``mesh``         — mesh construction over local/global devices; the
                     ("data", "seq") axes: documents across `data`,
                     a document's stripes across `seq` (the sequence-
                     parallel axis — a file larger than one chip's HBM
                     spans the `seq` axis).
* ``sharded_scan`` — shard_map'd scan step: each device scans its stripe
                     block locally; counts/results combine with psum /
                     all_gather over ICI.  Exactness across device
                     boundaries uses the same newline-reset + host
                     stitching story as single-device stripes.
* ``multihost``    — jax.distributed.initialize glue: each host's worker
                     process drives its local chips, while the
                     coordinator's four-verb protocol (runtime/) remains
                     the cross-host control plane over DCN.
"""

from distributed_grep_tpu.parallel.mesh import make_mesh
from distributed_grep_tpu.parallel.sharded_scan import sharded_grep_step

__all__ = ["make_mesh", "sharded_grep_step"]

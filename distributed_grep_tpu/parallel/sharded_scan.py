"""shard_map'd scan step: the multi-chip grep "training step".

Each device holds a contiguous block of document stripes (lanes) and runs
the same lane-parallel scan the single-chip engine uses; XLA collectives
combine results over ICI:

* per-device packed match bits stay device-local (fetched sparsely);
* the global match count is a psum over the mesh;
* exit states per stripe are returned for diagnostics / cross-shard
  continuation (a ppermute hands each device its left neighbor's last
  exit state — the ring pattern sequence parallelism uses, exercised here
  so the sharding compiles and runs even though grep's newline-reset +
  host stitching already gives exactness without it).

Everything is jit-compiled over an explicit Mesh with NamedShardings, so
the same code runs on one chip, a v5e pod slice, or the CI host's
8-virtual-device CPU mesh (SURVEY.md §4).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_grep_tpu.models.dfa import DfaTable
from distributed_grep_tpu.models.shift_and import ShiftAndModel
from distributed_grep_tpu.ops import scan_jnp
from distributed_grep_tpu.parallel.mesh import lane_sharding

NL = 0x0A


def _dfa_device_scan(data_blk, trans_flat, byte_to_cls, accept, accept_eol, start, n_classes):
    """Per-device body: (chunk, local_lanes) uint8 -> (packed bits, count,
    per-lane exit states).  Delegates the recurrence to scan_jnp.dfa_scan_body
    (single source of truth for scan semantics)."""
    # Derive the initial state vector from the (device-varying) data block so
    # the scan carry is varying over the shard_map axis — a replicated init
    # would fail the carry-type check against the varying output.
    init = (data_blk[0] * 0).astype(jnp.int32) + start
    final_states, match = scan_jnp.dfa_scan_body(
        data_blk, trans_flat, byte_to_cls, accept, accept_eol, init, n_classes
    )
    return scan_jnp._pack_lane_bits(match), jnp.count_nonzero(match), final_states


@partial(
    jax.jit,
    static_argnames=("mesh", "axis", "n_classes"),
)
def _sharded_dfa_scan(
    data_cl,  # (chunk, lanes) uint8, lanes sharded over `axis`
    trans_flat,
    byte_to_cls,
    accept,
    accept_eol,
    start,
    *,
    mesh: Mesh,
    axis: str,
    n_classes: int,
):
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    n_total = int(np.prod([mesh.shape[a] for a in axes]))

    def body(data_blk, trans_flat, byte_to_cls, accept, accept_eol, start):
        packed, count, exits = _dfa_device_scan(
            data_blk, trans_flat, byte_to_cls, accept, accept_eol, start, n_classes
        )
        total = jax.lax.psum(count, axes)  # ICI collective: global match count
        # Ring handoff of the rightmost stripe's exit state to the right
        # neighbor — the sequence-parallel state-carry pattern.  Lanes are
        # sharded over the linearized product of `axes` (lane_sharding is
        # axes-major in the given order), so the ring must wrap over that
        # same linear order: passing the axes tuple to ppermute flattens
        # them, making perm indices the linearized device positions.
        right_edge = exits[-1:]  # (1,) last lane's final state per device
        left_in = jax.lax.ppermute(
            right_edge,
            axes if len(axes) > 1 else axes[0],
            perm=[(i, (i + 1) % n_total) for i in range(n_total)],
        )
        return packed, total, exits, left_in

    from jax.experimental.shard_map import shard_map

    spec_lanes = P(None, axes)
    out = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec_lanes, P(), P(), P(), P(), P()),
        out_specs=(spec_lanes, P(), P(axes), P(axes)),
    )(data_cl, trans_flat, byte_to_cls, accept, accept_eol, start)
    return out


def stack_bank_tables(tables: list[DfaTable], n_shards: int):
    """Pad + stack per-bank DFA tables for the pattern-parallel step.

    Banks get padded to a common (n_states, n_classes) shape (padding rows
    are a dead state-0 loop with accept=False, so they can never match) and
    the bank count is padded to a multiple of n_shards.  Returns
    (trans_flat (B, S*C) int32, byte_to_cls (B, 256) int32,
    accept (B, S) bool, starts (B,) int32, n_classes_max)."""
    s_max = max(t.trans.shape[0] for t in tables)
    c_max = max(t.n_classes for t in tables)
    b_pad = -len(tables) % n_shards
    B = len(tables) + b_pad
    trans = np.zeros((B, s_max, c_max), dtype=np.int32)
    b2c = np.zeros((B, 256), dtype=np.int32)
    accept = np.zeros((B, s_max), dtype=bool)
    starts = np.zeros(B, dtype=np.int32)
    for i, t in enumerate(tables):
        s, c = t.trans.shape
        trans[i, :s, :c] = t.trans.astype(np.int32)
        b2c[i] = t.byte_to_cls.astype(np.int32)
        accept[i, :s] = t.accept
        starts[i] = t.start
        if t.accept_eol.any():
            raise ValueError("pattern-set banks never use accept_eol")
    return trans.reshape(B, -1), b2c, accept, starts, c_max


@partial(jax.jit, static_argnames=("mesh", "data_axis", "pattern_axis", "n_classes"))
def _sharded_pattern_set_scan(
    data_cl, trans_flat, b2c, accept, starts, *, mesh, data_axis, pattern_axis, n_classes
):
    def body(data_blk, trans_b, b2c_b, accept_b, starts_b):
        # Each device: its lane block vs its local pattern banks (unrolled —
        # bank count per device is static).
        local = trans_b.shape[0]
        hit = None
        for i in range(local):
            init = (data_blk[0] * 0).astype(jnp.int32) + starts_b[i]
            _, match = scan_jnp.dfa_scan_body(
                data_blk, trans_b[i], b2c_b[i], accept_b[i],
                jnp.zeros_like(accept_b[i]), init, n_classes,
            )
            hit = match if hit is None else (hit | match)
        # OR across the pattern axis: psum of the 0/1 plane, then > 0.  This
        # is the EP-analogue combine — each chip saw only its bank shard.
        any_hit = jax.lax.psum(hit.astype(jnp.int32), pattern_axis) > 0
        # any_hit is now invariant over the pattern axis; the global count
        # only needs the data-axis reduction.
        count = jax.lax.psum(jnp.count_nonzero(any_hit), data_axis)
        return scan_jnp._pack_lane_bits(any_hit), count

    from jax.experimental.shard_map import shard_map

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(None, data_axis),  # lanes sharded over data, replicated over pattern
            P(pattern_axis), P(pattern_axis), P(pattern_axis), P(pattern_axis),
        ),
        out_specs=(P(None, data_axis), P()),
    )(data_cl, trans_flat, b2c, accept, starts)


def sharded_pattern_set_step(
    data_cl: np.ndarray,
    tables: list[DfaTable],
    mesh: Mesh,
    data_axis: str = "data",
    pattern_axis: str = "seq",
):
    """Pattern-parallel multi-chip scan — the expert-parallel analogue
    (SURVEY.md §2 parallelism checklist): Hyperscan-style ruleset banks
    shard across ``pattern_axis`` while document lanes shard across
    ``data_axis``; each chip scans its lane block against only its banks
    and the per-position OR rides ICI (psum over the pattern axis).

    Returns (packed_bits (chunk, lanes//8) — the OR over all banks — and
    the global matched-position count).  Output is exact away from stripe
    boundaries; boundary lines get the usual host stitching."""
    n_pat = mesh.shape[pattern_axis]
    n_dat = mesh.shape[data_axis]
    chunk, lanes = data_cl.shape
    if lanes % (n_dat * 8):
        raise ValueError(f"lanes={lanes} must divide {data_axis}={n_dat} x 8")
    trans_flat, b2c, accept, starts, c_max = stack_bank_tables(tables, n_pat)
    dev_arr = jax.device_put(
        jnp.asarray(data_cl), NamedSharding(mesh, P(None, data_axis))
    )
    return _sharded_pattern_set_scan(
        dev_arr,
        jnp.asarray(trans_flat), jnp.asarray(b2c),
        jnp.asarray(accept), jnp.asarray(starts),
        mesh=mesh, data_axis=data_axis, pattern_axis=pattern_axis,
        n_classes=c_max,
    )


def sharded_grep_step(
    data_cl: np.ndarray,
    table: DfaTable,
    mesh: Mesh,
    axis: str | tuple[str, ...] = "data",
):
    """Run the sharded DFA scan; returns (packed_bits_device, total_count,
    exit_states, neighbor_states).  `axis` may be one mesh axis name or a
    tuple (e.g. ("data", "seq")) — lanes shard over the product.  Lanes must
    divide evenly by the sharded device count (layout.choose_layout
    lane_multiple handles this)."""
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    n_dev = int(np.prod([mesh.shape[a] for a in axes]))
    chunk, lanes = data_cl.shape
    if lanes % (n_dev * 8):
        raise ValueError(f"lanes={lanes} must divide mesh axes {axes} ({n_dev}) x 8")
    dev_arr = jax.device_put(jnp.asarray(data_cl), lane_sharding(mesh, axes))
    return _sharded_dfa_scan(
        dev_arr,
        jnp.asarray(table.trans.astype(np.int32).reshape(-1)),
        jnp.asarray(table.byte_to_cls.astype(np.int32)),
        jnp.asarray(table.accept),
        jnp.asarray(table.accept_eol),
        jnp.int32(table.start),
        mesh=mesh,
        axis=axis,
        n_classes=table.n_classes,
    )

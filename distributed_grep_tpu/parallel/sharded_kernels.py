"""shard_map'd Pallas kernel steps: the production kernels on N chips.

`sharded_scan.py` validates the collective patterns (psum, ppermute ring)
over the XLA DFA core; THIS module runs the engine's real production
kernels — shift-and (ops/pallas_scan), FDR (ops/pallas_fdr), Glushkov NFA
(ops/pallas_nfa) — under `shard_map` over an explicit Mesh, so the
multi-chip-validated path and the fast path are the same code:

* document lanes shard over the mesh axis (contiguous stripe blocks per
  device — cross-device boundaries are ordinary stripe boundaries, handled
  by the host stitch pass like any other);
* each device runs the UNCHANGED single-chip Pallas kernel on its lane
  block (the kernels are grid-sequential per device already);
* the global candidate count rides ICI as a psum — the cross-check the
  driver's dryrun asserts against the host oracle.

On the CI host the kernels run in interpret mode on the 8-virtual-device
CPU mesh; on a pod slice the same `shard_map` compiles to per-chip Mosaic
kernels + ICI collectives.  The engine's `mesh=` option (ops/engine.py)
dispatches segments through these steps, so `dryrun_multichip` and a real
multi-chip `GrepEngine` exercise identical scan code (VERDICT r2 item 1).

The reference fans its scan across workers one whole file per task
(coordinator.go:329-333); lanes-over-mesh is the TPU-native form of that
fan-out, with the psum replacing the coordinator-side tally.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_grep_tpu.ops import pallas_fdr, pallas_nfa, pallas_scan
from distributed_grep_tpu.ops.pallas_scan import (
    CHUNK_BLOCK_WORDS,
    LANE_COLS,
    LANES_PER_BLOCK,
    SUBLANES,
)


def _axes_tuple(axis) -> tuple[str, ...]:
    return (axis,) if isinstance(axis, str) else tuple(axis)


def mesh_lane_multiple(mesh: Mesh, axis) -> int:
    """Lanes must split into whole Pallas lane-blocks per device."""
    n_dev = int(np.prod([mesh.shape[a] for a in _axes_tuple(axis)]))
    return n_dev * LANES_PER_BLOCK


def _to_tiles(arr_cl: np.ndarray, mesh: Mesh, axis) -> np.ndarray:
    """(chunk, lanes) -> (chunk, S, 128) tiles, S shardable over `axis`.

    This is byte-for-byte the reshape the single-device wrappers perform
    (pallas_scan.shift_and_scan_words et al. — lane of row (S, l) is
    S*128 + l); sharding S contiguously therefore hands each device exactly
    the block a single-device run over its lanes would see, and the global
    output array decodes with the unchanged ops/sparse helpers."""
    chunk, lanes = arr_cl.shape
    steps = 32 * CHUNK_BLOCK_WORDS
    mult = mesh_lane_multiple(mesh, axis)
    if lanes % mult or chunk % steps:
        raise ValueError(
            f"sharded pallas layout needs lanes%{mult}==0 (got {lanes}), "
            f"chunk%{steps}==0 (got {chunk})"
        )
    return np.ascontiguousarray(arr_cl.reshape(chunk, lanes // LANE_COLS, LANE_COLS))


def _local_shard_index_map(sharding, shape, process_index: int | None = None):
    """{device: global-index} for exactly the shards THIS process must
    materialize — the multi-host feed contract (VERDICT r3 item 2): on a
    mesh spanning hosts, a process may only device_put onto its own
    addressable devices.  Pure over the sharding object so a mocked
    2-process topology can pin the subsetting without real federation
    (unavailable on this host — CLAUDE.md)."""
    if process_index is None:
        process_index = jax.process_index()
    return {
        d: idx
        for d, idx in sharding.devices_indices_map(tuple(shape)).items()
        if d.process_index == process_index
    }


def _put_spec(arr: np.ndarray, mesh: Mesh, spec: P) -> jnp.ndarray:
    """Host array -> device array sharded per ``spec``.

    Single-process: one device_put straight from host memory (wrapping in
    jnp.asarray first would land the whole array on the default device and
    pay an ICI reshard on top).  Multi-process (a mesh spanning hosts, as
    jax.distributed configures — parallel/multihost.py): device_put of the
    full host array would try to address remote devices, so each process
    instead materializes ONLY its local lane blocks and assembles the
    global array from single-device shards (the explicit form of
    jax.make_array_from_process_local_data).  The reference's data plane
    genuinely crossed machines (coordinator.go:195-265); this is that
    capability on the compute feed."""
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() > 1:
        shards = [
            jax.device_put(arr[idx], d)
            for d, idx in _local_shard_index_map(sharding, arr.shape).items()
        ]
        return jax.make_array_from_single_device_arrays(
            arr.shape, sharding, shards
        )
    return jax.device_put(arr, sharding)


def _put_sharded(tiles: np.ndarray, mesh: Mesh, axes) -> jnp.ndarray:
    return _put_spec(tiles, mesh, P(None, axes, None))


def prepare_tiles(arr_cl: np.ndarray, mesh: Mesh, axis) -> jnp.ndarray:
    """Host (chunk, lanes) -> device-resident lane-sharded (chunk, S, 128)
    tiles.  The engine's feed thread calls this for segment i+1 while
    segment i dispatches, so the reshape copy and the sharded upload
    overlap compute; every sharded_* wrapper below accepts the result in
    place of the host array."""
    return _put_sharded(_to_tiles(arr_cl, mesh, axis), mesh, _axes_tuple(axis))


def _tiles_for(arr_cl, mesh: Mesh, axis):
    """Accept either a host (chunk, lanes) array or already-prepared
    device tiles (ndim 3, from prepare_tiles)."""
    if getattr(arr_cl, "ndim", 2) == 3:
        return arr_cl
    return prepare_tiles(arr_cl, mesh, axis)


def _shard_shell(body, mesh: Mesh, axes, n_consts: int):
    """Wrap a per-device kernel body in the common shard_map shell: lanes
    sharded, constants replicated, psum'd nonzero-word count out."""
    from jax.experimental.shard_map import shard_map

    def shard_body(blk, *cs):
        words = body(blk, *cs)
        total = jax.lax.psum(jnp.count_nonzero(words), axes)
        return words, total

    spec = P(None, axes, None)
    return shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(spec,) + (P(),) * n_consts,
        out_specs=(spec, P()),
        # pallas_call's out_shape carries no varying-mesh-axes annotation,
        # so the replication checker cannot see through it; correctness is
        # pinned by the vs-single-device tests instead (test_parallel.py).
        check_rep=False,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "sym_ranges", "match_bit", "chunk", "coarse", "interpret", "mesh", "axes",
    ),
)
def _sharded_shift_and(
    tiles, *, sym_ranges, match_bit, chunk, coarse, interpret, mesh, axes
):
    def body(blk):
        return pallas_scan._shift_and_pallas(
            blk,
            sym_ranges=sym_ranges,
            match_bit=match_bit,
            chunk=chunk,
            lane_blocks=blk.shape[1] // SUBLANES,
            interpret=interpret,
            coarse=coarse,
        )

    return _shard_shell(body, mesh, axes, 0)(tiles)


def sharded_shift_and_words(
    arr_cl: np.ndarray,
    model,
    mesh: Mesh,
    axis="data",
    coarse: bool = True,
    interpret: bool | None = None,
):
    """Shift-and kernel over the mesh.  Returns (words, total): `words` is
    the global time-packed array in the shared device convention — identical
    values to a single-device `shift_and_scan_words` over the same layout —
    and `total` the psum'd nonzero-word count (candidate spans when coarse,
    else words containing >= 1 match bit)."""
    if interpret is None:
        interpret = not pallas_scan.available()
    if not pallas_scan.eligible(model):
        raise ValueError("pattern exceeds the pallas compare budget")
    axes = _axes_tuple(axis)
    tiles = _tiles_for(arr_cl, mesh, axis)
    return _sharded_shift_and(
        tiles,
        sym_ranges=tuple(tuple(r) for r in model.sym_ranges),
        match_bit=int(model.match_bit),
        chunk=int(arr_cl.shape[0]),
        coarse=coarse,
        interpret=interpret,
        mesh=mesh,
        axes=axes,
    )


@functools.partial(
    jax.jit,
    static_argnames=("ms", "plans", "chunk", "interpret", "mesh", "axes",
                     "fold_case"),
)
def _sharded_fdr(tiles, *tabs, ms, plans, chunk, interpret, mesh, axes,
                 fold_case=False):
    def body(blk, *cs):
        words = None
        for m, plan, tab in zip(ms, plans, cs):
            w = pallas_fdr._fdr_pallas(
                blk,
                tab,
                m=m,
                plan=plan,
                chunk=chunk,
                lane_blocks=blk.shape[1] // SUBLANES,
                interpret=interpret,
                fold_case=fold_case,
            )
            words = w if words is None else words | w
        return words

    return _shard_shell(body, mesh, axes, len(tabs))(tiles, *tabs)


def sharded_fdr_words(
    arr_cl: np.ndarray,
    fdr_model,
    mesh: Mesh,
    axis="data",
    interpret: bool | None = None,
    dev_tables: list | None = None,
    fold_case: bool = False,
):
    """FDR filter over the mesh: every bank's kernel runs per device on its
    lane block (tables replicated — they are KBs; the data is the big
    operand) and candidate words OR on device before leaving.  Returns
    (words, total) like the single-device path + psum'd candidate count."""
    if interpret is None:
        interpret = not pallas_scan.available()
    banks = fdr_model.banks
    for b in banks:
        if not pallas_fdr.eligible(b):
            raise ValueError("bank outside the kernel's check/domain budget")
    axes = _axes_tuple(axis)
    tiles = _tiles_for(arr_cl, mesh, axis)
    if dev_tables is None:
        dev_tables = [jnp.asarray(pallas_fdr.bank_device_tables(b)) for b in banks]
    return _sharded_fdr(
        tiles,
        *dev_tables,
        ms=tuple(b.m for b in banks),
        plans=tuple(pallas_fdr.kernel_plan(b) for b in banks),
        chunk=int(arr_cl.shape[0]),
        interpret=interpret,
        mesh=mesh,
        axes=axes,
        fold_case=fold_case,
    )


@functools.partial(
    jax.jit,
    static_argnames=("plan", "gather_b", "chunk", "interpret", "mesh", "axes",
                     "unroll"),
)
def _sharded_nfa(tiles, *b_tabs, plan, gather_b, chunk, interpret, mesh, axes,
                 unroll=16):
    def body(blk, *cs):
        return pallas_nfa._nfa_pallas(
            blk,
            cs[0] if gather_b else None,
            plan=plan,
            chunk=chunk,
            lane_blocks=blk.shape[1] // SUBLANES,
            gather_b=gather_b,
            interpret=interpret,
            unroll=unroll,
        )

    return _shard_shell(body, mesh, axes, len(b_tabs))(tiles, *b_tabs)


def sharded_nfa_words(
    arr_cl: np.ndarray,
    model,
    mesh: Mesh,
    axis="data",
    interpret: bool | None = None,
):
    """Glushkov NFA kernel over the mesh; (words, total) as above."""
    if interpret is None:
        interpret = not pallas_scan.available()
    if not pallas_nfa.eligible(model):
        raise ValueError("pattern exceeds the pallas NFA cost budget")
    axes = _axes_tuple(axis)
    tiles = _tiles_for(arr_cl, mesh, axis)
    gather_b = pallas_nfa.use_gather_b(model)
    b_tabs = (
        (jnp.asarray(pallas_nfa.build_b_tables(model)),) if gather_b else ()
    )
    return _sharded_nfa(
        tiles,
        *b_tabs,
        plan=model.kernel_plan(),
        gather_b=gather_b,
        chunk=int(arr_cl.shape[0]),
        interpret=interpret,
        mesh=mesh,
        axes=axes,
        unroll=pallas_nfa.unroll_for(model),
    )


@functools.partial(
    jax.jit,
    static_argnames=("m", "plan", "chunk", "interpret", "mesh",
                     "data_axes", "pattern_axes", "fold_case"),
)
def _sharded_fdr_pattern(tiles, tabs, *, m, plan, chunk, interpret, mesh,
                         data_axes, pattern_axes, fold_case=False):
    from jax.experimental.shard_map import shard_map

    def body(blk, tab_blk):
        words = None
        for i in range(tab_blk.shape[0]):  # local banks (static count)
            w = pallas_fdr._fdr_pallas(
                blk,
                tab_blk[i],
                m=m,
                plan=plan,
                chunk=chunk,
                lane_blocks=blk.shape[1] // SUBLANES,
                interpret=interpret,
                fold_case=fold_case,
            )
            words = w if words is None else words | w
        # candidate words must OR bitwise across the pattern axis (psum
        # would add colliding bits, pmax would drop them): all_gather the
        # small per-device words and reduce locally — the EP combine.
        gathered = jax.lax.all_gather(words, pattern_axes)
        all_words = jax.lax.reduce(
            gathered, jnp.uint32(0), jax.lax.bitwise_or, (0,)
        )
        total = jax.lax.psum(
            jnp.count_nonzero(all_words), data_axes + pattern_axes
        ) // np.prod([mesh.shape[a] for a in pattern_axes])
        return all_words, total

    spec = P(None, data_axes, None)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, P(pattern_axes)),
        out_specs=(spec, P()),
        check_rep=False,
    )(tiles, tabs)


def fdr_pattern_tables(fdr_model, mesh: Mesh, pattern_axis="seq") -> jnp.ndarray:
    """Stacked per-bank device tables, padded to the pattern-axis width
    with all-zero tables (zero reach = no candidates) and sharded over it.
    Engines cache this per plan (round-3 advisor finding: rebuilding +
    re-uploading the stack per segment swamped multi-segment EP scans)."""
    pattern_axes = _axes_tuple(pattern_axis)
    n_pat = int(np.prod([mesh.shape[a] for a in pattern_axes]))
    tabs = [pallas_fdr.bank_device_tables(b) for b in fdr_model.banks]
    pad = -len(tabs) % n_pat
    tabs += [np.zeros_like(tabs[0])] * pad
    stacked = np.stack(tabs)  # (B, rows, SUBLANES, LANE_COLS)
    return _put_spec(stacked, mesh, P(pattern_axes))


def sharded_fdr_pattern_step(
    arr_cl: np.ndarray,
    fdr_model,
    mesh: Mesh,
    data_axis="data",
    pattern_axis="seq",
    interpret: bool | None = None,
    fold_case: bool = False,
    tabs_dev: jnp.ndarray | None = None,
):
    """Pattern-parallel FDR: filter BANKS shard over ``pattern_axis`` while
    document lanes shard over ``data_axis`` — the expert-parallel analogue
    (SURVEY.md §2) on the PRODUCTION kernel rather than the XLA DFA banks
    (`sharded_scan.sharded_pattern_set_step`).

    Same-plan banks (what `models/fdr._compile_group` emits when it shards
    one group 2/4-way) differ only in table VALUES, so the whole bank
    dimension is a shardable operand: every device runs the identical
    kernel program on its lane block with its local table shard, per-chip
    gather cost drops by the pattern-axis width, and candidate words OR
    across ICI (all_gather + bitwise-or — candidates must stay a bitwise
    union for the host confirm to decode).  Returns (words, total) in the
    usual convention; `words` is bit-identical to a single-device OR over
    all banks.  Bank count pads to the axis width with all-zero tables
    (zero reach = no candidates)."""
    if interpret is None:
        interpret = not pallas_scan.available()
    banks = fdr_model.banks
    plans = {(b.m, pallas_fdr.kernel_plan(b)) for b in banks}
    if len(plans) != 1:
        raise ValueError(
            "pattern-parallel FDR needs same-plan banks (mixed-window "
            "models keep the lane-sharded step)"
        )
    (m, plan), = plans
    for b in banks:
        if not pallas_fdr.eligible(b):
            raise ValueError("bank outside the kernel's check/domain budget")
    data_axes = _axes_tuple(data_axis)
    pattern_axes = _axes_tuple(pattern_axis)
    tiles = _tiles_for(arr_cl, mesh, data_axis)
    if tabs_dev is None:
        tabs_dev = fdr_pattern_tables(fdr_model, mesh, pattern_axis)
    return _sharded_fdr_pattern(
        tiles,
        tabs_dev,
        m=m,
        plan=plan,
        chunk=int(arr_cl.shape[0]),
        interpret=interpret,
        mesh=mesh,
        data_axes=data_axes,
        pattern_axes=pattern_axes,
        fold_case=fold_case,
    )


@functools.partial(
    jax.jit,
    static_argnames=("chunk", "transposed", "fold_case", "interpret",
                     "mesh", "axes"),
)
def _sharded_pairset(tiles, tabs, *, chunk, transposed, fold_case, interpret,
                     mesh, axes):
    from distributed_grep_tpu.ops import pallas_pairset

    def body(blk, tab):
        return pallas_pairset._pairset_pallas(
            blk,
            tab,
            chunk=chunk,
            lane_blocks=blk.shape[1] // SUBLANES,
            transposed=transposed,
            fold_case=fold_case,
            interpret=interpret,
        )

    return _shard_shell(body, mesh, axes, 1)(tiles, tabs)


def sharded_pairset_words(
    arr_cl: np.ndarray,
    model,
    mesh: Mesh,
    axis="data",
    interpret: bool | None = None,
    dev_tables=None,
):
    """Exact short-set pair kernel over the mesh; (words, total) in the
    shared convention — the words are exact match ends, so the psum total
    counts matches, not candidates.  ``dev_tables`` lets the engine upload
    the table array once and reuse across segments (like
    sharded_fdr_words)."""
    from distributed_grep_tpu.ops import pallas_pairset

    if interpret is None:
        interpret = not pallas_scan.available()
    if not pallas_pairset.eligible(model):
        raise ValueError("pairset model outside the kernel budget")
    axes = _axes_tuple(axis)
    tiles = _tiles_for(arr_cl, mesh, axis)
    if dev_tables is None:
        dev_tables = jnp.asarray(pallas_pairset.device_tables(model))
    return _sharded_pairset(
        tiles,
        dev_tables,
        chunk=int(arr_cl.shape[0]),
        transposed=model.transposed,
        fold_case=model.ignore_case,
        interpret=interpret,
        mesh=mesh,
        axes=axes,
    )


@functools.partial(
    jax.jit,
    static_argnames=("sym_ranges", "match_bit", "k", "chunk", "interpret",
                     "mesh", "axes"),
)
def _sharded_approx(tiles, *, sym_ranges, match_bit, k, chunk, interpret,
                    mesh, axes):
    from distributed_grep_tpu.ops import pallas_approx

    def body(blk):
        return pallas_approx._approx_pallas(
            blk,
            sym_ranges=sym_ranges,
            match_bit=match_bit,
            k=k,
            chunk=chunk,
            lane_blocks=blk.shape[1] // SUBLANES,
            interpret=interpret,
        )

    return _shard_shell(body, mesh, axes, 0)(tiles)


def sharded_approx_words(
    arr_cl: np.ndarray,
    model,
    mesh: Mesh,
    axis="data",
    interpret: bool | None = None,
):
    """Approx (agrep <=k errors) kernel over the mesh; (words, total) in
    the shared convention — completes the set: every Pallas engine the
    single-chip bench runs has a shard_map'd multi-chip form."""
    from distributed_grep_tpu.ops import pallas_approx

    if interpret is None:
        interpret = not pallas_scan.available()
    if not pallas_approx.eligible(model):
        raise ValueError("model exceeds the pallas approx budget")
    axes = _axes_tuple(axis)
    tiles = _tiles_for(arr_cl, mesh, axis)
    return _sharded_approx(
        tiles,
        sym_ranges=tuple(tuple(r) for r in model.base.sym_ranges),
        match_bit=int(model.match_bit),
        k=model.k,
        chunk=int(arr_cl.shape[0]),
        interpret=interpret,
        mesh=mesh,
        axes=axes,
    )

"""Mesh construction helpers.

A grep job's mesh has up to two axes:

* ``data`` — independent document shards (the reference's one-task-per-file
  axis, coordinator.go:329-333, generalized to many chips);
* ``seq``  — stripes *within* one document: the sequence-parallel axis for
  documents bigger than a chip (the long-context axis, SURVEY.md §5).

Both axes are interchangeable for throughput (the scan is lane-parallel
either way); they differ in how results recombine — `data` concatenates,
`seq` needs boundary-line stitching, which ops/lines.py handles uniformly
because device boundaries are just stripe boundaries.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    shape: tuple[int, ...] = (),
    axes: tuple[str, ...] = ("data",),
    devices: list | None = None,
) -> Mesh:
    """Build a Mesh; shape () means all devices on the first axis."""
    devs = devices if devices is not None else jax.devices()
    if not shape:
        shape = (len(devs),) + (1,) * (len(axes) - 1)
    n = int(np.prod(shape))
    if n > len(devs):
        raise ValueError(f"mesh shape {shape} needs {n} devices, have {len(devs)}")
    arr = np.asarray(devs[:n]).reshape(shape)
    return Mesh(arr, axes)


def lane_sharding(mesh: Mesh, axis: str | tuple[str, ...] = "data") -> NamedSharding:
    """Sharding for the (chunk, lanes) stripe array: lanes split across the
    given mesh axis (or axis tuple — lanes shard over the product) — each
    device owns a contiguous block of document stripes, so cross-device
    boundaries are ordinary stripe boundaries."""
    return NamedSharding(mesh, P(None, axis))

// libdgrep — native host-side hot loops for distributed_grep_tpu.
//
// The reference implements its runtime in compiled Go; the TPU-native build
// keeps the runtime's hot host-side loops native too (the TPU compute path
// is JAX/XLA/Pallas; this library covers what runs on the host):
//
//   * fnv32a        — FNV-32a partition hash (reference: ihash,
//                     map_reduce/worker.go:13-17; partition = hash % nReduce,
//                     worker.go:89).
//   * newline_index — newline offset scan (memchr loop) used to slice match
//                     byte-offsets into grep line numbers without Python
//                     per-byte loops.
//   * literal_scan  — memmem-based literal substring scan emitting match end
//                     offsets; CPU fallback engine + oracle for kernels.
//   * dfa_scan      — table-driven DFA byte scan emitting accept offsets;
//                     the host-side oracle for the Pallas DFA kernel.
//
// Build: make -C native   (produces libdgrep.so; loaded via ctypes from
// distributed_grep_tpu/utils/native.py, with pure-Python fallbacks).

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <thread>
#include <vector>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

extern "C" {

// FNV-32a over `len` bytes, masked to non-negative int32 like the reference
// does (worker.go:13-17 masks with 0x7fffffff).
uint32_t dgrep_fnv32a(const uint8_t* data, size_t len) {
    uint32_t h = 2166136261u;
    for (size_t i = 0; i < len; ++i) {
        h ^= data[i];
        h *= 16777619u;
    }
    return h & 0x7fffffffu;
}

// Write byte offsets of every '\n' into out (capacity max_out).
// Returns the total number of newlines found (may exceed max_out; caller
// re-calls with a bigger buffer in that case).
size_t dgrep_newline_index(const uint8_t* data, size_t len,
                           uint64_t* out, size_t max_out) {
    size_t count = 0;
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    while (p < end) {
        const uint8_t* nl = (const uint8_t*)memchr(p, '\n', (size_t)(end - p));
        if (!nl) break;
        if (count < max_out) out[count] = (uint64_t)(nl - data);
        ++count;
        p = nl + 1;
    }
    return count;
}

// Find end-offsets (offset of last byte + 1) of every occurrence of
// `needle` in `hay` (overlapping occurrences included, matching regex
// scan-all semantics). Returns total count; writes up to max_out offsets.
size_t dgrep_literal_scan(const uint8_t* hay, size_t hay_len,
                          const uint8_t* needle, size_t needle_len,
                          uint64_t* out, size_t max_out) {
    if (needle_len == 0 || needle_len > hay_len) return 0;
    size_t count = 0;
#if defined(__AVX2__)
    if (needle_len >= 2) {
        // SIMD first/last-byte filter (Mula's "SIMD-friendly substring
        // search"): candidate start positions are those where the needle's
        // first byte matches a 32-wide block AND its last byte matches the
        // block shifted by needle_len-1; only candidates run the memcmp.
        // Measured on this host vs the glibc-memmem loop below: 3.3-3.6 vs
        // 1.7-1.8 GB/s on random lowercase text (1000 planted needles),
        // and wider on English text where the needle's bytes are rarer.
        const __m256i first = _mm256_set1_epi8((char)needle[0]);
        const __m256i last = _mm256_set1_epi8((char)needle[needle_len - 1]);
        size_t i = 0;
        while (i + needle_len - 1 + 32 <= hay_len) {
            __m256i b0 = _mm256_loadu_si256((const __m256i*)(hay + i));
            __m256i b1 = _mm256_loadu_si256(
                (const __m256i*)(hay + i + needle_len - 1));
            uint32_t mask = (uint32_t)_mm256_movemask_epi8(_mm256_and_si256(
                _mm256_cmpeq_epi8(b0, first), _mm256_cmpeq_epi8(b1, last)));
            while (mask) {
                unsigned b = (unsigned)__builtin_ctz(mask);
                mask &= mask - 1;
                if (memcmp(hay + i + b + 1, needle + 1, needle_len - 2) == 0) {
                    if (count < max_out)
                        out[count] = (uint64_t)(i + b) + needle_len;
                    ++count;
                }
            }
            i += 32;
        }
        for (; i + needle_len <= hay_len; ++i) {  // scalar tail
            if (hay[i] == needle[0] &&
                memcmp(hay + i + 1, needle + 1, needle_len - 1) == 0) {
                if (count < max_out) out[count] = (uint64_t)i + needle_len;
                ++count;
            }
        }
        return count;
    }
#endif
    const uint8_t* p = hay;
    const uint8_t* end = hay + hay_len;
    while (p + needle_len <= end) {
        const uint8_t* hit =
            (const uint8_t*)memmem(p, (size_t)(end - p), needle, needle_len);
        if (!hit) break;
        if (count < max_out)
            out[count] = (uint64_t)(hit - hay) + needle_len;
        ++count;
        p = hit + 1;  // overlapping matches
    }
    return count;
}

// Table-driven DFA scan. `table` is row-major [n_states][256] uint16 next
// states; `accept` is a per-state 0/1 byte map. Starts in `start_state`,
// feeds every byte, records offset i+1 whenever the post-transition state is
// accepting. Returns total accept count (writes up to max_out offsets) and
// stores the final state in *final_state (for cross-chunk state carry).
size_t dgrep_dfa_scan(const uint8_t* data, size_t len,
                      const uint16_t* table, const uint8_t* accept,
                      uint32_t start_state,
                      uint64_t* out, size_t max_out,
                      uint32_t* final_state) {
    uint32_t s = start_state;
    size_t count = 0;
    for (size_t i = 0; i < len; ++i) {
        s = table[((size_t)s << 8) | data[i]];
        if (accept[s]) {
            if (count < max_out) out[count] = (uint64_t)i + 1;
            ++count;
        }
    }
    if (final_state) *final_state = s;
    return count;
}

// Multithreaded DFA scan.  Chunk boundaries snap to the byte AFTER a
// newline; because every state's '\n' transition is the start state (the
// newline-reset invariant all tables here share, models/dfa.py DfaTable),
// scanning each chunk from start_state produces byte-identical output to
// the sequential scan — the same property the device path's stripe layout
// exploits.  Offsets are written in ascending order; returns the total
// accept count (writes up to max_out).
size_t dgrep_dfa_scan_mt(const uint8_t* data, size_t len,
                         const uint16_t* table, const uint8_t* accept,
                         uint32_t start_state,
                         uint64_t* out, size_t max_out,
                         uint32_t n_threads) {
    if (n_threads < 2 || len < (size_t)n_threads * 4096) {
        uint32_t fin;
        return dgrep_dfa_scan(data, len, table, accept, start_state,
                              out, max_out, &fin);
    }
    std::vector<size_t> bounds;
    bounds.push_back(0);
    for (uint32_t t = 1; t < n_threads; ++t) {
        size_t want = len * t / n_threads;
        if (want <= bounds.back()) continue;
        const void* nl = memchr(data + want, '\n', len - want);
        size_t b = nl ? (size_t)((const uint8_t*)nl - data) + 1 : len;
        if (b > bounds.back() && b < len) bounds.push_back(b);
    }
    bounds.push_back(len);

    size_t parts = bounds.size() - 1;
    std::vector<std::vector<uint64_t>> hits(parts);
    std::vector<std::thread> threads;
    for (size_t p = 0; p < parts; ++p) {
        threads.emplace_back([&, p]() {
            size_t lo = bounds[p], hi = bounds[p + 1];
            uint32_t s = start_state;
            std::vector<uint64_t>& h = hits[p];
            for (size_t i = lo; i < hi; ++i) {
                s = table[((size_t)s << 8) | data[i]];
                if (accept[s]) h.push_back((uint64_t)i + 1);
            }
        });
    }
    for (auto& th : threads) th.join();

    size_t count = 0;
    for (size_t p = 0; p < parts; ++p) {
        for (uint64_t off : hits[p]) {
            if (count < max_out) out[count] = off;
            ++count;
        }
    }
    return count;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Literal-set candidate confirm: the host side of the FDR filter path
// (models/fdr.py).  The device filter emits candidate END offsets (offset of
// last byte + 1); each candidate is confirmed by probing a hash table keyed
// on the last 4 bytes of the pattern and memcmp'ing the full literal.  This
// replaces re-scanning each candidate's whole line through the Aho-Corasick
// DFA (~120 ns/candidate) with a ~10 ns probe, which is what lets the FDR
// tuner trade filter passes for candidates (fewer device lookups per byte).
// ---------------------------------------------------------------------------

struct DgrepConfirmSlot {
    uint32_t key;   // last-4-byte key owning this slot (valid when head >= 0)
    int32_t head;   // first pattern idx sharing the key, or -1 for empty
};

struct DgrepConfirmSet {
    std::vector<uint8_t> pat_bytes;       // folded copy when ci
    std::vector<uint32_t> pat_off;        // n+1 prefix offsets into pat_bytes
    std::vector<DgrepConfirmSlot> slots;  // open addressing, linear probe;
                                          // one slot per distinct key, so a
                                          // non-candidate rejects on the
                                          // first (usually only) cacheline
    std::vector<int32_t> next;            // same-key pattern chain link
    std::vector<uint32_t> shorts;         // indices of patterns with len < 4
    std::vector<uint8_t> bloom;           // L1-resident bitmap over the key
                                          // hash's high 18 bits: rejects the
                                          // ~96% absent-key majority without
                                          // touching the (L2-sized) slots
    uint32_t mask = 0;                    // table size - 1 (power of two)
    bool has_fold = false;                // ignore_case: fold data bytes
    uint8_t fold[256];                    // identity, or ASCII tolower when ci
};

// 2^18-bit bloom = 32 KB: fits L1 alongside the streamed data; at 10k keys
// the bit density is ~4%, so an absent key (the common case by construction
// — the device filter's false candidates rarely have their exact 4-byte
// suffix in the set) is rejected by one predictable L1 load.
static constexpr uint32_t DGREP_BLOOM_BYTES = 1u << 15;
static inline uint32_t dgrep_bloom_bit(uint32_t h) { return h >> 14; }

static inline uint32_t dgrep_confirm_hash(uint32_t key) {
    key *= 2654435761u;  // Knuth multiplicative mix
    return key ^ (key >> 15);
}

extern "C" {

// Build a confirm set from concatenated pattern bytes + n+1 prefix offsets.
// Patterns must be pre-normalized (lowercased when ignore_case) by the
// caller — `ignore_case` here only controls folding of the *data* bytes.
void* dgrep_confirm_build(const uint8_t* pat_bytes, const uint32_t* pat_off,
                          uint32_t n, int ignore_case) {
    auto* cs = new DgrepConfirmSet();
    cs->has_fold = ignore_case != 0;
    cs->pat_bytes.assign(pat_bytes, pat_bytes + pat_off[n]);
    cs->pat_off.assign(pat_off, pat_off + n + 1);
    for (int i = 0; i < 256; ++i)
        cs->fold[i] = (uint8_t)((ignore_case && i >= 'A' && i <= 'Z')
                                    ? i - 'A' + 'a' : i);
    uint32_t bits = 2;
    while ((1u << bits) < 4 * n + 4) ++bits;  // load factor <= 0.25
    cs->mask = (1u << bits) - 1;
    cs->slots.assign((size_t)cs->mask + 1, DgrepConfirmSlot{0u, -1});
    cs->next.assign(n, -1);
    cs->bloom.assign(DGREP_BLOOM_BYTES, 0);
    for (uint32_t i = 0; i < n; ++i) {
        uint32_t len = pat_off[i + 1] - pat_off[i];
        if (len < 4) {
            cs->shorts.push_back(i);
            continue;
        }
        const uint8_t* tail = cs->pat_bytes.data() + pat_off[i + 1] - 4;
        uint32_t key;
        memcpy(&key, tail, 4);
        uint32_t hb = dgrep_bloom_bit(dgrep_confirm_hash(key));
        cs->bloom[hb >> 3] |= (uint8_t)(1u << (hb & 7));
        uint32_t s = dgrep_confirm_hash(key) & cs->mask;
        while (cs->slots[s].head >= 0 && cs->slots[s].key != key)
            s = (s + 1) & cs->mask;  // linear probe to the key's slot
        cs->next[i] = cs->slots[s].head;
        cs->slots[s] = DgrepConfirmSlot{key, (int32_t)i};
    }
    return cs;
}

void dgrep_confirm_free(void* handle) {
    delete (DgrepConfirmSet*)handle;
}

}  // extern "C"

// Confirm one candidate range.  Per-candidate cost measured on the build
// host (2.1 GHz Xeon, 2026-07-30): the naive loop runs at ~9 ns/candidate —
// 4 fold loads + a probe into the L2-sized slots table with a poorly
// predicted occupancy branch.  The fast path below runs at ~2.5 ns:
//
//   * no-fold specialization (one unaligned u32 load for the key),
//   * a 32 KB L1-resident bloom bitmap over the key hash rejects the
//     absent-key majority (~96% of device-filter false candidates) with
//     one predictable load — the slots table is only touched by survivors,
//   * a rolling prefetch keeps the streamed corpus ahead of the key loads
//     (candidates arrive sorted, so data access is near-sequential).
//
// This constant is what the FDR tuner prices device filtering against
// (models/fdr.py CONFIRM_PS_PER_CANDIDATE): a 3.6x cheaper confirm buys a
// ~25% cheaper device filter at equal total cost.
template <bool FOLD, bool SHORTS>
static void dgrep_confirm_range_t(const DgrepConfirmSet* cs,
                                  const uint8_t* data, size_t len,
                                  const uint64_t* cand,
                                  size_t lo, size_t hi, uint8_t* out) {
    constexpr size_t P = 24;  // data prefetch distance (candidates)
    const uint8_t* f = cs->fold;
    const uint8_t* bloom = cs->bloom.data();
    for (size_t i = lo; i < hi; ++i) {
        if (i + P < hi) {
            uint64_t ep = cand[i + P];
            if (ep >= 4 && ep <= len) __builtin_prefetch(data + ep - 4, 0, 3);
        }
        uint64_t e = cand[i];
        bool hit = false;
        if (e <= len && e >= 4) {
            uint32_t key;
            if (FOLD) {
                uint8_t kb[4] = {f[data[e - 4]], f[data[e - 3]],
                                 f[data[e - 2]], f[data[e - 1]]};
                memcpy(&key, kb, 4);
            } else {
                memcpy(&key, data + e - 4, 4);
            }
            uint32_t h = dgrep_confirm_hash(key);
            uint32_t hb = dgrep_bloom_bit(h);
            if (bloom[hb >> 3] & (1u << (hb & 7))) {
                uint32_t s = h & cs->mask;
                while (cs->slots[s].head >= 0) {  // empty slot: key absent
                    if (cs->slots[s].key == key) {
                        for (int32_t pi = cs->slots[s].head; pi >= 0;
                             pi = cs->next[pi]) {
                            uint32_t plen =
                                cs->pat_off[pi + 1] - cs->pat_off[pi];
                            if (plen > e) continue;
                            const uint8_t* p =
                                cs->pat_bytes.data() + cs->pat_off[pi];
                            const uint8_t* d = data + e - plen;
                            uint32_t k = 0;
                            if (FOLD) {
                                for (; k < plen && p[k] == f[d[k]]; ++k) {}
                            } else {
                                for (; k < plen && p[k] == d[k]; ++k) {}
                            }
                            if (k == plen) { hit = true; break; }
                        }
                        break;
                    }
                    s = (s + 1) & cs->mask;
                }
            }
        }
        if (SHORTS && !hit && e > 0 && e <= len) {
            for (uint32_t si : cs->shorts) {
                uint32_t plen = cs->pat_off[si + 1] - cs->pat_off[si];
                if (plen > e) continue;
                const uint8_t* p = cs->pat_bytes.data() + cs->pat_off[si];
                const uint8_t* d = data + e - plen;
                uint32_t k = 0;
                for (; k < plen && (FOLD ? p[k] == f[d[k]] : p[k] == d[k]);
                     ++k) {}
                if (k == plen) { hit = true; break; }
            }
        }
        out[i] = hit ? 1 : 0;
    }
}

static void dgrep_confirm_range(const DgrepConfirmSet* cs, const uint8_t* data,
                                size_t len, const uint64_t* cand,
                                size_t lo, size_t hi, uint8_t* out,
                                bool fold, bool shorts) {
    auto fn = fold ? (shorts ? dgrep_confirm_range_t<true, true>
                             : dgrep_confirm_range_t<true, false>)
                   : (shorts ? dgrep_confirm_range_t<false, true>
                             : dgrep_confirm_range_t<false, false>);
    fn(cs, data, len, cand, lo, hi, out);
}

extern "C" {

// Confirm candidate end-offsets against the set; out[i] = 1 when some
// pattern truly ends at cand[i].  Threads split the candidate array.
void dgrep_confirm_scan(const void* handle, const uint8_t* data, size_t len,
                        const uint64_t* cand, size_t n_cand, uint8_t* out,
                        uint32_t n_threads) {
    const auto* cs = (const DgrepConfirmSet*)handle;
    bool fold = cs->has_fold, shorts = !cs->shorts.empty();
    if (n_threads < 2 || n_cand < 4096) {
        dgrep_confirm_range(cs, data, len, cand, 0, n_cand, out, fold, shorts);
        return;
    }
    std::vector<std::thread> threads;
    for (uint32_t t = 0; t < n_threads; ++t) {
        size_t lo = n_cand * t / n_threads, hi = n_cand * (t + 1) / n_threads;
        threads.emplace_back([=]() {
            dgrep_confirm_range(cs, data, len, cand, lo, hi, out, fold, shorts);
        });
    }
    for (auto& th : threads) th.join();
}

}  // extern "C"

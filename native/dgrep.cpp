// libdgrep — native host-side hot loops for distributed_grep_tpu.
//
// The reference implements its runtime in compiled Go; the TPU-native build
// keeps the runtime's hot host-side loops native too (the TPU compute path
// is JAX/XLA/Pallas; this library covers what runs on the host):
//
//   * fnv32a        — FNV-32a partition hash (reference: ihash,
//                     map_reduce/worker.go:13-17; partition = hash % nReduce,
//                     worker.go:89).
//   * newline_index — newline offset scan (memchr loop) used to slice match
//                     byte-offsets into grep line numbers without Python
//                     per-byte loops.
//   * literal_scan  — memmem-based literal substring scan emitting match end
//                     offsets; CPU fallback engine + oracle for kernels.
//   * dfa_scan      — table-driven DFA byte scan emitting accept offsets;
//                     the host-side oracle for the Pallas DFA kernel.
//
// Build: make -C native   (produces libdgrep.so; loaded via ctypes from
// distributed_grep_tpu/utils/native.py, with pure-Python fallbacks).

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <thread>
#include <vector>

extern "C" {

// FNV-32a over `len` bytes, masked to non-negative int32 like the reference
// does (worker.go:13-17 masks with 0x7fffffff).
uint32_t dgrep_fnv32a(const uint8_t* data, size_t len) {
    uint32_t h = 2166136261u;
    for (size_t i = 0; i < len; ++i) {
        h ^= data[i];
        h *= 16777619u;
    }
    return h & 0x7fffffffu;
}

// Write byte offsets of every '\n' into out (capacity max_out).
// Returns the total number of newlines found (may exceed max_out; caller
// re-calls with a bigger buffer in that case).
size_t dgrep_newline_index(const uint8_t* data, size_t len,
                           uint64_t* out, size_t max_out) {
    size_t count = 0;
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    while (p < end) {
        const uint8_t* nl = (const uint8_t*)memchr(p, '\n', (size_t)(end - p));
        if (!nl) break;
        if (count < max_out) out[count] = (uint64_t)(nl - data);
        ++count;
        p = nl + 1;
    }
    return count;
}

// Find end-offsets (offset of last byte + 1) of every occurrence of
// `needle` in `hay` (overlapping occurrences included, matching regex
// scan-all semantics). Returns total count; writes up to max_out offsets.
size_t dgrep_literal_scan(const uint8_t* hay, size_t hay_len,
                          const uint8_t* needle, size_t needle_len,
                          uint64_t* out, size_t max_out) {
    if (needle_len == 0 || needle_len > hay_len) return 0;
    size_t count = 0;
    const uint8_t* p = hay;
    const uint8_t* end = hay + hay_len;
    while (p + needle_len <= end) {
        const uint8_t* hit =
            (const uint8_t*)memmem(p, (size_t)(end - p), needle, needle_len);
        if (!hit) break;
        if (count < max_out)
            out[count] = (uint64_t)(hit - hay) + needle_len;
        ++count;
        p = hit + 1;  // overlapping matches
    }
    return count;
}

// Table-driven DFA scan. `table` is row-major [n_states][256] uint16 next
// states; `accept` is a per-state 0/1 byte map. Starts in `start_state`,
// feeds every byte, records offset i+1 whenever the post-transition state is
// accepting. Returns total accept count (writes up to max_out offsets) and
// stores the final state in *final_state (for cross-chunk state carry).
size_t dgrep_dfa_scan(const uint8_t* data, size_t len,
                      const uint16_t* table, const uint8_t* accept,
                      uint32_t start_state,
                      uint64_t* out, size_t max_out,
                      uint32_t* final_state) {
    uint32_t s = start_state;
    size_t count = 0;
    for (size_t i = 0; i < len; ++i) {
        s = table[((size_t)s << 8) | data[i]];
        if (accept[s]) {
            if (count < max_out) out[count] = (uint64_t)i + 1;
            ++count;
        }
    }
    if (final_state) *final_state = s;
    return count;
}

// Multithreaded DFA scan.  Chunk boundaries snap to the byte AFTER a
// newline; because every state's '\n' transition is the start state (the
// newline-reset invariant all tables here share, models/dfa.py DfaTable),
// scanning each chunk from start_state produces byte-identical output to
// the sequential scan — the same property the device path's stripe layout
// exploits.  Offsets are written in ascending order; returns the total
// accept count (writes up to max_out).
size_t dgrep_dfa_scan_mt(const uint8_t* data, size_t len,
                         const uint16_t* table, const uint8_t* accept,
                         uint32_t start_state,
                         uint64_t* out, size_t max_out,
                         uint32_t n_threads) {
    if (n_threads < 2 || len < (size_t)n_threads * 4096) {
        uint32_t fin;
        return dgrep_dfa_scan(data, len, table, accept, start_state,
                              out, max_out, &fin);
    }
    std::vector<size_t> bounds;
    bounds.push_back(0);
    for (uint32_t t = 1; t < n_threads; ++t) {
        size_t want = len * t / n_threads;
        if (want <= bounds.back()) continue;
        const void* nl = memchr(data + want, '\n', len - want);
        size_t b = nl ? (size_t)((const uint8_t*)nl - data) + 1 : len;
        if (b > bounds.back() && b < len) bounds.push_back(b);
    }
    bounds.push_back(len);

    size_t parts = bounds.size() - 1;
    std::vector<std::vector<uint64_t>> hits(parts);
    std::vector<std::thread> threads;
    for (size_t p = 0; p < parts; ++p) {
        threads.emplace_back([&, p]() {
            size_t lo = bounds[p], hi = bounds[p + 1];
            uint32_t s = start_state;
            std::vector<uint64_t>& h = hits[p];
            for (size_t i = lo; i < hi; ++i) {
                s = table[((size_t)s << 8) | data[i]];
                if (accept[s]) h.push_back((uint64_t)i + 1);
            }
        });
    }
    for (auto& th : threads) th.join();

    size_t count = 0;
    for (size_t p = 0; p < parts; ++p) {
        for (uint64_t off : hits[p]) {
            if (count < max_out) out[count] = off;
            ++count;
        }
    }
    return count;
}

}  // extern "C"

// libdgrep — native host-side hot loops for distributed_grep_tpu.
//
// The reference implements its runtime in compiled Go; the TPU-native build
// keeps the runtime's hot host-side loops native too (the TPU compute path
// is JAX/XLA/Pallas; this library covers what runs on the host):
//
//   * fnv32a        — FNV-32a partition hash (reference: ihash,
//                     map_reduce/worker.go:13-17; partition = hash % nReduce,
//                     worker.go:89).
//   * newline_index — newline offset scan (memchr loop) used to slice match
//                     byte-offsets into grep line numbers without Python
//                     per-byte loops.
//   * literal_scan  — memmem-based literal substring scan emitting match end
//                     offsets; CPU fallback engine + oracle for kernels.
//   * dfa_scan      — table-driven DFA byte scan emitting accept offsets;
//                     the host-side oracle for the Pallas DFA kernel.
//
// Build: make -C native   (produces libdgrep.so; loaded via ctypes from
// distributed_grep_tpu/utils/native.py, with pure-Python fallbacks).

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <thread>
#include <vector>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

extern "C" {

// FNV-32a over `len` bytes, masked to non-negative int32 like the reference
// does (worker.go:13-17 masks with 0x7fffffff).
uint32_t dgrep_fnv32a(const uint8_t* data, size_t len) {
    uint32_t h = 2166136261u;
    for (size_t i = 0; i < len; ++i) {
        h ^= data[i];
        h *= 16777619u;
    }
    return h & 0x7fffffffu;
}

// Write byte offsets of every '\n' into out (capacity max_out).
// Returns the total number of newlines found (may exceed max_out; caller
// re-calls with a bigger buffer in that case).  SIMD path: on text-shaped
// corpora newlines land every few dozen bytes, so the memchr loop's
// per-hit call overhead dominates (~0.8 GB/s measured on the dense
// receipt); the AVX2 block compare + movemask bit walk runs ~4-5x that.
size_t dgrep_newline_index(const uint8_t* data, size_t len,
                           uint64_t* out, size_t max_out) {
    size_t count = 0;
#if defined(__AVX2__)
    const __m256i nl_v = _mm256_set1_epi8('\n');
    size_t i = 0;
    for (; i + 32 <= len; i += 32) {
        __m256i block = _mm256_loadu_si256((const __m256i*)(data + i));
        uint32_t mask = (uint32_t)_mm256_movemask_epi8(
            _mm256_cmpeq_epi8(block, nl_v));
        while (mask) {
            unsigned b = (unsigned)__builtin_ctz(mask);
            mask &= mask - 1;
            if (count < max_out) out[count] = (uint64_t)(i + b);
            ++count;
        }
    }
    for (; i < len; ++i) {  // scalar tail
        if (data[i] == '\n') {
            if (count < max_out) out[count] = (uint64_t)i;
            ++count;
        }
    }
    return count;
#else
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    while (p < end) {
        const uint8_t* nl = (const uint8_t*)memchr(p, '\n', (size_t)(end - p));
        if (!nl) break;
        if (count < max_out) out[count] = (uint64_t)(nl - data);
        ++count;
        p = nl + 1;
    }
    return count;
#endif
}

// Find end-offsets (offset of last byte + 1) of every occurrence of
// `needle` in `hay` (overlapping occurrences included, matching regex
// scan-all semantics). Returns total count; writes up to max_out offsets.
size_t dgrep_literal_scan(const uint8_t* hay, size_t hay_len,
                          const uint8_t* needle, size_t needle_len,
                          uint64_t* out, size_t max_out) {
    if (needle_len == 0 || needle_len > hay_len) return 0;
    size_t count = 0;
#if defined(__AVX2__)
    if (needle_len >= 2) {
        // SIMD first/last-byte filter (Mula's "SIMD-friendly substring
        // search"): candidate start positions are those where the needle's
        // first byte matches a 32-wide block AND its last byte matches the
        // block shifted by needle_len-1; only candidates run the memcmp.
        // Measured on this host vs the glibc-memmem loop below: 3.3-3.6 vs
        // 1.7-1.8 GB/s on random lowercase text (1000 planted needles),
        // and wider on English text where the needle's bytes are rarer.
        const __m256i first = _mm256_set1_epi8((char)needle[0]);
        const __m256i last = _mm256_set1_epi8((char)needle[needle_len - 1]);
        size_t i = 0;
        while (i + needle_len - 1 + 32 <= hay_len) {
            __m256i b0 = _mm256_loadu_si256((const __m256i*)(hay + i));
            __m256i b1 = _mm256_loadu_si256(
                (const __m256i*)(hay + i + needle_len - 1));
            uint32_t mask = (uint32_t)_mm256_movemask_epi8(_mm256_and_si256(
                _mm256_cmpeq_epi8(b0, first), _mm256_cmpeq_epi8(b1, last)));
            while (mask) {
                unsigned b = (unsigned)__builtin_ctz(mask);
                mask &= mask - 1;
                if (memcmp(hay + i + b + 1, needle + 1, needle_len - 2) == 0) {
                    if (count < max_out)
                        out[count] = (uint64_t)(i + b) + needle_len;
                    ++count;
                }
            }
            i += 32;
        }
        for (; i + needle_len <= hay_len; ++i) {  // scalar tail
            if (hay[i] == needle[0] &&
                memcmp(hay + i + 1, needle + 1, needle_len - 1) == 0) {
                if (count < max_out) out[count] = (uint64_t)i + needle_len;
                ++count;
            }
        }
        return count;
    }
#endif
    const uint8_t* p = hay;
    const uint8_t* end = hay + hay_len;
    while (p + needle_len <= end) {
        const uint8_t* hit =
            (const uint8_t*)memmem(p, (size_t)(end - p), needle, needle_len);
        if (!hit) break;
        if (count < max_out)
            out[count] = (uint64_t)(hit - hay) + needle_len;
        ++count;
        p = hit + 1;  // overlapping matches
    }
    return count;
}

// Table-driven DFA scan. `table` is row-major [n_states][256] uint16 next
// states; `accept` is a per-state 0/1 byte map. Starts in `start_state`,
// feeds every byte, records offset i+1 whenever the post-transition state is
// accepting. Returns total accept count (writes up to max_out offsets) and
// stores the final state in *final_state (for cross-chunk state carry).
size_t dgrep_dfa_scan(const uint8_t* data, size_t len,
                      const uint16_t* table, const uint8_t* accept,
                      uint32_t start_state,
                      uint64_t* out, size_t max_out,
                      uint32_t* final_state) {
    uint32_t s = start_state;
    size_t count = 0;
    for (size_t i = 0; i < len; ++i) {
        s = table[((size_t)s << 8) | data[i]];
        if (accept[s]) {
            if (count < max_out) out[count] = (uint64_t)i + 1;
            ++count;
        }
    }
    if (final_state) *final_state = s;
    return count;
}

// Multithreaded DFA scan.  Chunk boundaries snap to the byte AFTER a
// newline; because every state's '\n' transition is the start state (the
// newline-reset invariant all tables here share, models/dfa.py DfaTable),
// scanning each chunk from start_state produces byte-identical output to
// the sequential scan — the same property the device path's stripe layout
// exploits.  Offsets are written in ascending order; returns the total
// accept count (writes up to max_out).
size_t dgrep_dfa_scan_mt(const uint8_t* data, size_t len,
                         const uint16_t* table, const uint8_t* accept,
                         uint32_t start_state,
                         uint64_t* out, size_t max_out,
                         uint32_t n_threads) {
    if (n_threads < 2 || len < (size_t)n_threads * 4096) {
        uint32_t fin;
        return dgrep_dfa_scan(data, len, table, accept, start_state,
                              out, max_out, &fin);
    }
    std::vector<size_t> bounds;
    bounds.push_back(0);
    for (uint32_t t = 1; t < n_threads; ++t) {
        size_t want = len * t / n_threads;
        if (want <= bounds.back()) continue;
        const void* nl = memchr(data + want, '\n', len - want);
        size_t b = nl ? (size_t)((const uint8_t*)nl - data) + 1 : len;
        if (b > bounds.back() && b < len) bounds.push_back(b);
    }
    bounds.push_back(len);

    size_t parts = bounds.size() - 1;
    std::vector<std::vector<uint64_t>> hits(parts);
    std::vector<std::thread> threads;
    for (size_t p = 0; p < parts; ++p) {
        threads.emplace_back([&, p]() {
            size_t lo = bounds[p], hi = bounds[p + 1];
            uint32_t s = start_state;
            std::vector<uint64_t>& h = hits[p];
            for (size_t i = lo; i < hi; ++i) {
                s = table[((size_t)s << 8) | data[i]];
                if (accept[s]) h.push_back((uint64_t)i + 1);
            }
        });
    }
    for (auto& th : threads) th.join();

    size_t count = 0;
    for (size_t p = 0; p < parts; ++p) {
        for (uint64_t off : hits[p]) {
            if (count < max_out) out[count] = off;
            ++count;
        }
    }
    return count;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Literal-set candidate confirm: the host side of the FDR filter path
// (models/fdr.py).  The device filter emits candidate END offsets (offset of
// last byte + 1); each candidate is confirmed by probing a hash table keyed
// on the last 4 bytes of the pattern and memcmp'ing the full literal.  This
// replaces re-scanning each candidate's whole line through the Aho-Corasick
// DFA (~120 ns/candidate) with a ~10 ns probe, which is what lets the FDR
// tuner trade filter passes for candidates (fewer device lookups per byte).
// ---------------------------------------------------------------------------

struct DgrepConfirmSlot {
    uint32_t key;   // last-4-byte key owning this slot (valid when head >= 0)
    int32_t head;   // first pattern idx sharing the key, or -1 for empty
};

struct DgrepConfirmSet {
    std::vector<uint8_t> pat_bytes;       // folded copy when ci
    std::vector<uint32_t> pat_off;        // n+1 prefix offsets into pat_bytes
    std::vector<DgrepConfirmSlot> slots;  // open addressing, linear probe;
                                          // one slot per distinct key, so a
                                          // non-candidate rejects on the
                                          // first (usually only) cacheline
    std::vector<int32_t> next;            // same-key pattern chain link
    std::vector<uint32_t> shorts;         // indices of patterns with len < 4
    std::vector<uint8_t> bloom;           // L1-resident bitmap over the key
                                          // hash's high 18 bits: rejects the
                                          // ~96% absent-key majority without
                                          // touching the (L2-sized) slots
    uint32_t mask = 0;                    // table size - 1 (power of two)
    bool has_fold = false;                // ignore_case: fold data bytes
    uint8_t fold[256];                    // identity, or ASCII tolower when ci
};

// 2^18-bit bloom = 32 KB: fits L1 alongside the streamed data; at 10k keys
// the bit density is ~4%, so an absent key (the common case by construction
// — the device filter's false candidates rarely have their exact 4-byte
// suffix in the set) is rejected by one predictable L1 load.
static constexpr uint32_t DGREP_BLOOM_BYTES = 1u << 15;
static inline uint32_t dgrep_bloom_bit(uint32_t h) { return h >> 14; }

static inline uint32_t dgrep_confirm_hash(uint32_t key) {
    key *= 2654435761u;  // Knuth multiplicative mix
    return key ^ (key >> 15);
}

extern "C" {

// Build a confirm set from concatenated pattern bytes + n+1 prefix offsets.
// Patterns must be pre-normalized (lowercased when ignore_case) by the
// caller — `ignore_case` here only controls folding of the *data* bytes.
void* dgrep_confirm_build(const uint8_t* pat_bytes, const uint32_t* pat_off,
                          uint32_t n, int ignore_case) {
    auto* cs = new DgrepConfirmSet();
    cs->has_fold = ignore_case != 0;
    cs->pat_bytes.assign(pat_bytes, pat_bytes + pat_off[n]);
    cs->pat_off.assign(pat_off, pat_off + n + 1);
    for (int i = 0; i < 256; ++i)
        cs->fold[i] = (uint8_t)((ignore_case && i >= 'A' && i <= 'Z')
                                    ? i - 'A' + 'a' : i);
    uint32_t bits = 2;
    while ((1u << bits) < 4 * n + 4) ++bits;  // load factor <= 0.25
    cs->mask = (1u << bits) - 1;
    cs->slots.assign((size_t)cs->mask + 1, DgrepConfirmSlot{0u, -1});
    cs->next.assign(n, -1);
    cs->bloom.assign(DGREP_BLOOM_BYTES, 0);
    for (uint32_t i = 0; i < n; ++i) {
        uint32_t len = pat_off[i + 1] - pat_off[i];
        if (len < 4) {
            cs->shorts.push_back(i);
            continue;
        }
        const uint8_t* tail = cs->pat_bytes.data() + pat_off[i + 1] - 4;
        uint32_t key;
        memcpy(&key, tail, 4);
        uint32_t hb = dgrep_bloom_bit(dgrep_confirm_hash(key));
        cs->bloom[hb >> 3] |= (uint8_t)(1u << (hb & 7));
        uint32_t s = dgrep_confirm_hash(key) & cs->mask;
        while (cs->slots[s].head >= 0 && cs->slots[s].key != key)
            s = (s + 1) & cs->mask;  // linear probe to the key's slot
        cs->next[i] = cs->slots[s].head;
        cs->slots[s] = DgrepConfirmSlot{key, (int32_t)i};
    }
    return cs;
}

void dgrep_confirm_free(void* handle) {
    delete (DgrepConfirmSet*)handle;
}

}  // extern "C"

// Confirm one candidate range.  Per-candidate cost measured on the build
// host (2.1 GHz Xeon, 2026-07-30): the naive loop runs at ~9 ns/candidate —
// 4 fold loads + a probe into the L2-sized slots table with a poorly
// predicted occupancy branch.  The fast path below runs at ~2.5 ns:
//
//   * no-fold specialization (one unaligned u32 load for the key),
//   * a 32 KB L1-resident bloom bitmap over the key hash rejects the
//     absent-key majority (~96% of device-filter false candidates) with
//     one predictable load — the slots table is only touched by survivors,
//   * a rolling prefetch keeps the streamed corpus ahead of the key loads
//     (candidates arrive sorted, so data access is near-sequential).
//
// This constant is what the FDR tuner prices device filtering against
// (models/fdr.py CONFIRM_PS_PER_CANDIDATE): a 3.6x cheaper confirm buys a
// ~25% cheaper device filter at equal total cost.
template <bool FOLD, bool SHORTS>
static void dgrep_confirm_range_t(const DgrepConfirmSet* cs,
                                  const uint8_t* data, size_t len,
                                  const uint64_t* cand,
                                  size_t lo, size_t hi, uint8_t* out) {
    constexpr size_t P = 24;  // data prefetch distance (candidates)
    const uint8_t* f = cs->fold;
    const uint8_t* bloom = cs->bloom.data();
    for (size_t i = lo; i < hi; ++i) {
        if (i + P < hi) {
            uint64_t ep = cand[i + P];
            if (ep >= 4 && ep <= len) __builtin_prefetch(data + ep - 4, 0, 3);
        }
        uint64_t e = cand[i];
        bool hit = false;
        if (e <= len && e >= 4) {
            uint32_t key;
            if (FOLD) {
                uint8_t kb[4] = {f[data[e - 4]], f[data[e - 3]],
                                 f[data[e - 2]], f[data[e - 1]]};
                memcpy(&key, kb, 4);
            } else {
                memcpy(&key, data + e - 4, 4);
            }
            uint32_t h = dgrep_confirm_hash(key);
            uint32_t hb = dgrep_bloom_bit(h);
            if (bloom[hb >> 3] & (1u << (hb & 7))) {
                uint32_t s = h & cs->mask;
                while (cs->slots[s].head >= 0) {  // empty slot: key absent
                    if (cs->slots[s].key == key) {
                        for (int32_t pi = cs->slots[s].head; pi >= 0;
                             pi = cs->next[pi]) {
                            uint32_t plen =
                                cs->pat_off[pi + 1] - cs->pat_off[pi];
                            if (plen > e) continue;
                            const uint8_t* p =
                                cs->pat_bytes.data() + cs->pat_off[pi];
                            const uint8_t* d = data + e - plen;
                            uint32_t k = 0;
                            if (FOLD) {
                                for (; k < plen && p[k] == f[d[k]]; ++k) {}
                            } else {
                                for (; k < plen && p[k] == d[k]; ++k) {}
                            }
                            if (k == plen) { hit = true; break; }
                        }
                        break;
                    }
                    s = (s + 1) & cs->mask;
                }
            }
        }
        if (SHORTS && !hit && e > 0 && e <= len) {
            for (uint32_t si : cs->shorts) {
                uint32_t plen = cs->pat_off[si + 1] - cs->pat_off[si];
                if (plen > e) continue;
                const uint8_t* p = cs->pat_bytes.data() + cs->pat_off[si];
                const uint8_t* d = data + e - plen;
                uint32_t k = 0;
                for (; k < plen && (FOLD ? p[k] == f[d[k]] : p[k] == d[k]);
                     ++k) {}
                if (k == plen) { hit = true; break; }
            }
        }
        out[i] = hit ? 1 : 0;
    }
}

static void dgrep_confirm_range(const DgrepConfirmSet* cs, const uint8_t* data,
                                size_t len, const uint64_t* cand,
                                size_t lo, size_t hi, uint8_t* out,
                                bool fold, bool shorts) {
    auto fn = fold ? (shorts ? dgrep_confirm_range_t<true, true>
                             : dgrep_confirm_range_t<true, false>)
                   : (shorts ? dgrep_confirm_range_t<false, true>
                             : dgrep_confirm_range_t<false, false>);
    fn(cs, data, len, cand, lo, hi, out);
}

extern "C" {

// Confirm candidate end-offsets against the set; out[i] = 1 when some
// pattern truly ends at cand[i].  Threads split the candidate array.
void dgrep_confirm_scan(const void* handle, const uint8_t* data, size_t len,
                        const uint64_t* cand, size_t n_cand, uint8_t* out,
                        uint32_t n_threads) {
    const auto* cs = (const DgrepConfirmSet*)handle;
    bool fold = cs->has_fold, shorts = !cs->shorts.empty();
    if (n_threads < 2 || n_cand < 4096) {
        dgrep_confirm_range(cs, data, len, cand, 0, n_cand, out, fold, shorts);
        return;
    }
    std::vector<std::thread> threads;
    for (uint32_t t = 0; t < n_threads; ++t) {
        size_t lo = n_cand * t / n_threads, hi = n_cand * (t + 1) / n_threads;
        threads.emplace_back([=]() {
            dgrep_confirm_range(cs, data, len, cand, lo, hi, out, fold, shorts);
        });
    }
    for (auto& th : threads) th.join();
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Columnar merge/print hot loops (round 6).  The match-dense output path
// moves LineBatch slabs (runtime/columnar.py) around as bytes; the three
// per-record Python/numpy passes that still dominated the dense print job
// (BASELINE.md round-6 profile) become plain memcpy/merge loops here:
//
//   * gather_ranges   — concatenate arr[starts[i]:ends[i]] (the slab
//                       rebuild under LineBatch.select / make_batch /
//                       the display gather; numpy's cumsum-index gather
//                       moved ~10 bytes of index traffic per output byte).
//   * format_batch    — the mr-out text form "<prefix>N)<sep><line>\n"
//                       per record (LineBatch.format_lines).  Refuses
//                       non-UTF-8 slabs (-2): the Python path decodes
//                       utf-8/replace, so only strictly-valid slabs copy
//                       through byte-identically; the caller falls back.
//   * merge_display   — k-way merge of pre-sorted mr-out buffers into the
//                       final display bytes (tab -> space), ordered by
//                       (path, line) where paths compare as Python str —
//                       surrogateescape codepoints, NOT raw bytes (see
//                       se_cmp below; runtime/job._iter_records_bytes_sorted
//                       documents why byte order would misorder exotic
//                       filenames).  Refuses (-1) on any line that is not
//                       grep-key-shaped; the caller falls back.
// ---------------------------------------------------------------------------

extern "C" {

// out must hold sum(ends[i] - starts[i]) bytes (the caller's cumsum).
void dgrep_gather_ranges(const uint8_t* data, const int64_t* starts,
                         const int64_t* ends, size_t n, uint8_t* out) {
    uint8_t* p = out;
    for (size_t i = 0; i < n; ++i) {
        int64_t len = ends[i] - starts[i];
        if (len <= 0) continue;
        memcpy(p, data + starts[i], (size_t)len);
        p += len;
    }
}

// Strict UTF-8 validation (RFC 3629: no overlongs, no surrogates, max
// U+10FFFF) — exactly the inputs Python's utf-8 decode accepts, i.e. the
// inputs for which decode('utf-8','replace') then encode('utf-8') is the
// identity.  Returns 1 when valid.
int dgrep_utf8_valid(const uint8_t* p, size_t len) {
    const uint8_t* end = p + len;
    while (p < end) {
        uint8_t b = *p;
        if (b < 0x80) { ++p; continue; }
        if (b >= 0xC2 && b <= 0xDF) {
            if (end - p < 2 || (p[1] & 0xC0) != 0x80) return 0;
            p += 2; continue;
        }
        if (b >= 0xE0 && b <= 0xEF) {
            if (end - p < 3 || (p[1] & 0xC0) != 0x80 ||
                (p[2] & 0xC0) != 0x80) return 0;
            if (b == 0xE0 && p[1] < 0xA0) return 0;        // overlong
            if (b == 0xED && p[1] > 0x9F) return 0;        // surrogate
            p += 3; continue;
        }
        if (b >= 0xF0 && b <= 0xF4) {
            if (end - p < 4 || (p[1] & 0xC0) != 0x80 ||
                (p[2] & 0xC0) != 0x80 || (p[3] & 0xC0) != 0x80) return 0;
            if (b == 0xF0 && p[1] < 0x90) return 0;        // overlong
            if (b == 0xF4 && p[1] > 0x8F) return 0;        // > U+10FFFF
            p += 4; continue;
        }
        return 0;  // lone continuation byte or 0xC0/0xC1/0xF5+
    }
    return 1;
}

// Write "<prefix><decimal lineno>)<sep><line>\n" per record — byte-for-byte
// LineBatch.format_lines as encoded by the reduce writer (utf-8/
// surrogateescape), PROVIDED every LINE is strictly valid UTF-8 (checked
// per line range, NOT whole-slab: the Python path decodes per line, and
// two invalid line tails/heads can concatenate into valid slab bytes —
// whole-slab validity does not imply per-line identity.  The prefix
// needs no check — the Python path writes the filename's
// surrogateescape bytes verbatim either way).  Returns bytes written,
// -1 when out_cap is too small, -2 when some line needs Python's
// utf-8/replace semantics (caller falls back).
int64_t dgrep_format_batch(const uint8_t* prefix, size_t prefix_len,
                           const int64_t* linenos, const int64_t* offsets,
                           const uint8_t* slab, size_t n, uint8_t sep,
                           uint8_t* out, size_t out_cap) {
    if (n == 0) return 0;
    for (size_t i = 0; i < n; ++i)
        if (!dgrep_utf8_valid(slab + offsets[i],
                              (size_t)(offsets[i + 1] - offsets[i])))
            return -2;
    uint8_t* p = out;
    uint8_t* cap = out + out_cap;
    char digits[24];
    for (size_t i = 0; i < n; ++i) {
        int nd = 0;
        uint64_t v = (uint64_t)linenos[i];
        do { digits[nd++] = (char)('0' + v % 10); v /= 10; } while (v);
        int64_t line_len = offsets[i + 1] - offsets[i];
        if (p + prefix_len + nd + 3 + line_len > cap) return -1;
        memcpy(p, prefix, prefix_len);
        p += prefix_len;
        while (nd) *p++ = (uint8_t)digits[--nd];
        *p++ = ')';
        *p++ = sep;
        memcpy(p, slab + offsets[i], (size_t)line_len);
        p += line_len;
        *p++ = '\n';
    }
    return (int64_t)(p - out);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native map-record pipeline (round 8).  Everything between kernel output
// and the partitioned mr-out slabs used to be a chain of numpy passes
// (runtime/columnar.py: make_batch_from_lines -> partitions() ->
// per-partition select()/gather): line-span computation, an intermediate
// whole-batch slab gather, a vectorized-but-multi-pass FNV over the line
// numbers, then one more gather per partition.  The three entries below
// collapse that into ONE byte-touching pass:
//
//   * unique_lines   — sorted match end-offsets -> unique 1-based line
//                      numbers (linear merge against the newline index;
//                      replaces searchsorted + np.unique).
//   * line_spans     — [start, end) byte span per line from the newline
//                      index (the vectorized ops/lines.line_span; clip
//                      semantics mirror make_batch_from_lines exactly).
//   * build_records  — line spans in, per-reduce-partition LineBatch
//                      arrays out: FNV-32a of "<prefix><lineno>)" per
//                      record (bit-identical to fnv32a above — the
//                      reference ihash — as runtime/columnar.partitions
//                      already pins), stable partition grouping, and one
//                      memcpy per line straight into its partition's
//                      region of the output slab.
// ---------------------------------------------------------------------------

extern "C" {

// Unique 1-based line numbers containing sorted match END offsets (i+1
// convention: the match's last byte is at offset-1).  Equals
// np.unique(np.searchsorted(nl, ends - 1, 'right') + 1) for ascending
// `ends`; a linear merge because both arrays are sorted.  Returns the
// number of distinct lines written to out (capacity n suffices).
int64_t dgrep_unique_lines(const uint64_t* nl, int64_t n_nl,
                           const int64_t* ends, int64_t n,
                           int64_t* out) {
    int64_t count = 0;
    int64_t line = 0;  // index into nl: nl[line] is current line's '\n'
    int64_t last = 0;
    for (int64_t i = 0; i < n; ++i) {
        int64_t pos = ends[i] - 1;  // byte offset of the match's last byte
        while (line < n_nl && (int64_t)nl[line] <= pos) ++line;
        int64_t ln = line + 1;
        if (ln != last) {
            out[count++] = ln;
            last = ln;
        }
    }
    return count;
}

// [start, end) byte span per 1-based line number from the newline index
// (end excludes the '\n').  Mirrors the numpy clip semantics of
// runtime/columnar.make_batch_from_lines bit for bit, including its
// defensive clamping of out-of-range line numbers.
void dgrep_line_spans(const uint64_t* nl, int64_t n_nl,
                      const int64_t* linenos, int64_t n, int64_t n_bytes,
                      int64_t* starts, int64_t* ends) {
    if (n_nl == 0) {  // chunk with no newline: only line 1 exists
        for (int64_t i = 0; i < n; ++i) { starts[i] = 0; ends[i] = n_bytes; }
        return;
    }
    for (int64_t i = 0; i < n; ++i) {
        int64_t ln = linenos[i];
        int64_t a = ln - 2;
        if (a < 0) a = 0; else if (a >= n_nl) a = n_nl - 1;
        starts[i] = (ln == 1) ? 0 : (int64_t)nl[a] + 1;
        int64_t b = ln - 1;
        if (b < 0) b = 0; else if (b >= n_nl) b = n_nl - 1;
        ends[i] = (ln - 1 < n_nl) ? (int64_t)nl[b] : n_bytes;
    }
}

// One-pass partitioned record build.  Inputs: the source bytes, one
// [start, end) span + one STORED line number per record (spans come from
// dgrep_line_spans over local numbers, or from a built batch's offsets),
// and the pre-encoded key prefix "<filename> (line number #".  Outputs,
// grouped by partition in ascending partition order with the original
// record order preserved inside each partition (exactly what
// np.flatnonzero-based select() produced):
//
//   out_linenos [n]     stored line numbers, grouped
//   out_offsets [n+1]   GLOBAL slab offsets of the grouped records (each
//                       partition's own offsets array = the slice minus
//                       its byte base — contiguity makes that exact)
//   out_slab            gathered line bytes, grouped (caller sizes it as
//                       sum(end-start))
//   out_counts [n_reduce], out_bytes [n_reduce]  per-partition totals
//
// The per-record hash is FNV-32a over "<prefix><decimal lineno>)" —
// bit-identical to dgrep_fnv32a on the formatted key; partition =
// (h & 0x7fffffff) % n_reduce (reference ihash semantics).  Returns the
// total slab bytes written, or -1 on a malformed span (caller falls back
// to the numpy path).
int64_t dgrep_build_records(const uint8_t* data, int64_t data_len,
                            const int64_t* starts, const int64_t* ends,
                            const int64_t* linenos, int64_t n,
                            const uint8_t* prefix, int64_t prefix_len,
                            int32_t n_reduce,
                            int64_t* out_linenos, int64_t* out_offsets,
                            uint8_t* out_slab,
                            int64_t* out_counts, int64_t* out_bytes) {
    if (n_reduce <= 0) return -1;
    uint32_t h0 = 2166136261u;
    for (int64_t i = 0; i < prefix_len; ++i) {
        h0 ^= prefix[i];
        h0 *= 16777619u;
    }
    for (int32_t p = 0; p < n_reduce; ++p) {
        out_counts[p] = 0;
        out_bytes[p] = 0;
    }
    std::vector<int32_t> part((size_t)n);
    for (int64_t i = 0; i < n; ++i) {
        int64_t s = starts[i], e = ends[i];
        if (s < 0 || e > data_len || e < s) return -1;
        char digits[24];
        int nd = 0;
        uint64_t v = (uint64_t)linenos[i];
        do { digits[nd++] = (char)('0' + v % 10); v /= 10; } while (v);
        uint32_t h = h0;
        while (nd) {  // decimal digits fold most-significant first
            h ^= (uint8_t)digits[--nd];
            h *= 16777619u;
        }
        h ^= (uint8_t)')';
        h *= 16777619u;
        int32_t p = (int32_t)((h & 0x7fffffffu) % (uint32_t)n_reduce);
        part[(size_t)i] = p;
        out_counts[p] += 1;
        out_bytes[p] += e - s;
    }
    std::vector<int64_t> rec_at((size_t)n_reduce), byte_at((size_t)n_reduce);
    int64_t rec_base = 0, byte_base = 0;
    for (int32_t p = 0; p < n_reduce; ++p) {
        rec_at[(size_t)p] = rec_base;
        byte_at[(size_t)p] = byte_base;
        rec_base += out_counts[p];
        byte_base += out_bytes[p];
    }
    for (int64_t i = 0; i < n; ++i) {
        int32_t p = part[(size_t)i];
        int64_t len = ends[i] - starts[i];
        int64_t ri = rec_at[(size_t)p]++;
        int64_t bi = byte_at[(size_t)p];
        byte_at[(size_t)p] += len;
        out_linenos[ri] = linenos[i];
        out_offsets[ri] = bi;
        if (len) memcpy(out_slab + bi, data + starts[i], (size_t)len);
    }
    out_offsets[n] = byte_base;
    return byte_base;
}

}  // extern "C"

// --- surrogateescape string comparison -------------------------------------
// Python's display merge orders records by the DECODED path
// (utf-8/surrogateescape -> str), compared by codepoint.  Codepoint order
// diverges from byte order exactly where a valid multi-byte sequence
// (codepoint < U+DC00) meets a surrogate-escaped raw byte (0xDC00 + b >=
// 0xDC80), so the native merge must decode to compare.

static inline int se_is_cont(uint8_t b) { return (b & 0xC0) == 0x80; }

// Decode ONE codepoint at p (strict UTF-8; any invalid byte becomes
// 0xDC00 + byte and advances 1, the surrogateescape handler's behavior).
static inline uint32_t se_next(const uint8_t* p, const uint8_t* end,
                               int* adv) {
    uint8_t b = p[0];
    if (b < 0x80) { *adv = 1; return b; }
    if (b >= 0xC2 && b <= 0xDF && end - p >= 2 && se_is_cont(p[1])) {
        *adv = 2;
        return ((uint32_t)(b & 0x1F) << 6) | (p[1] & 0x3F);
    }
    if (b >= 0xE0 && b <= 0xEF && end - p >= 3 && se_is_cont(p[1]) &&
        se_is_cont(p[2]) && !(b == 0xE0 && p[1] < 0xA0) &&
        !(b == 0xED && p[1] > 0x9F)) {
        *adv = 3;
        return ((uint32_t)(b & 0x0F) << 12) |
               ((uint32_t)(p[1] & 0x3F) << 6) | (p[2] & 0x3F);
    }
    if (b >= 0xF0 && b <= 0xF4 && end - p >= 4 && se_is_cont(p[1]) &&
        se_is_cont(p[2]) && se_is_cont(p[3]) &&
        !(b == 0xF0 && p[1] < 0x90) && !(b == 0xF4 && p[1] > 0x8F)) {
        *adv = 4;
        return ((uint32_t)(b & 0x07) << 18) |
               ((uint32_t)(p[1] & 0x3F) << 12) |
               ((uint32_t)(p[2] & 0x3F) << 6) | (p[3] & 0x3F);
    }
    *adv = 1;
    return 0xDC00u + b;
}

// Compare two byte strings as their surrogateescape-decoded str forms.
// Fast path: scan to the first differing byte; byte-equal strings are
// equal.  Everywhere else — including the full-common-prefix case, where
// "shorter sorts first" would be WRONG if the shorter string ends
// mid-sequence of the longer's valid UTF-8 codepoint (b"foo\xC3" decodes
// to U+DCC3 and sorts AFTER b"foo\xC3\xA9"'s U+00E9) — back up to a safe
// decode boundary in the common prefix (every non-continuation byte is a
// true boundary — valid sequences have continuation-only interiors and
// invalid bytes decode standalone; after skipping <= 3 continuation
// bytes, an adjacent lead byte is included so a codepoint straddling the
// divergence decodes whole) and compare decoded codepoints from there;
// the decode loop's exhaustion handling yields codepoint-prefix order.
static int se_cmp(const uint8_t* a, size_t alen,
                  const uint8_t* b, size_t blen) {
    size_t common = alen < blen ? alen : blen;
    size_t i = 0;
    while (i < common && a[i] == b[i]) ++i;
    if (i == common && alen == blen) return 0;
    size_t j = i;
    int k = 0;
    while (j > 0 && k < 3 && se_is_cont(a[j - 1])) { --j; ++k; }
    if (j > 0 && a[j - 1] >= 0xC0) --j;
    const uint8_t *pa = a + j, *pb = b + j;
    const uint8_t *ea = a + alen, *eb = b + blen;
    while (pa < ea && pb < eb) {
        int adva, advb;
        uint32_t ca = se_next(pa, ea, &adva);
        uint32_t cb = se_next(pb, eb, &advb);
        if (ca != cb) return ca < cb ? -1 : 1;
        pa += adva;
        pb += advb;
    }
    if (pa < ea) return 1;
    if (pb < eb) return -1;
    return 0;
}

// --- k-way display merge ---------------------------------------------------

struct DgrepMergeCursor {
    const uint8_t* pos;        // next unread byte of this buffer
    const uint8_t* end;
    const uint8_t* line;       // current record's line start
    size_t line_len;           // excluding '\n'
    const uint8_t* path;       // parsed key: path bytes
    size_t path_len;
    uint64_t lineno;
    size_t tab;                // offset of '\t' in line, or line_len
    int idx;                   // buffer index (merge tie-break, heapq order)
};

static const uint8_t DGREP_KEY_MARKER[] = " (line number #";
static const size_t DGREP_KEY_MARKER_LEN = sizeof(DGREP_KEY_MARKER) - 1;

// Advance to the cursor's next nonempty line and parse its grep key.
// Returns 1 on a record, 0 at end-of-buffer, -1 on a non-grep-shaped line.
static int dgrep_merge_advance(DgrepMergeCursor* c) {
    for (;;) {
        if (c->pos >= c->end) return 0;
        const uint8_t* nl = (const uint8_t*)memchr(
            c->pos, '\n', (size_t)(c->end - c->pos));
        const uint8_t* eol = nl ? nl : c->end;
        const uint8_t* line = c->pos;
        c->pos = nl ? nl + 1 : c->end;
        size_t len = (size_t)(eol - line);
        if (len == 0) continue;  // skip empty lines (the Python merge does)
        const uint8_t* tab = (const uint8_t*)memchr(line, '\t', len);
        size_t key_len = tab ? (size_t)(tab - line) : len;
        // key must end "...#<digits>)" with the marker before the digits
        if (key_len < DGREP_KEY_MARKER_LEN + 2 || line[key_len - 1] != ')')
            return -1;
        size_t d = key_len - 1;  // scan digits backwards
        while (d > 0 && line[d - 1] >= '0' && line[d - 1] <= '9') --d;
        if (d == key_len - 1 || d < DGREP_KEY_MARKER_LEN) return -1;
        if (memcmp(line + d - DGREP_KEY_MARKER_LEN, DGREP_KEY_MARKER,
                   DGREP_KEY_MARKER_LEN) != 0)
            return -1;
        if (key_len - 1 - d > 19) return -1;  // int64 overflow guard
        uint64_t v = 0;
        for (size_t q = d; q < key_len - 1; ++q) v = v * 10 + (line[q] - '0');
        c->line = line;
        c->line_len = len;
        c->path = line;
        c->path_len = d - DGREP_KEY_MARKER_LEN;
        c->lineno = v;
        c->tab = tab ? (size_t)(tab - line) : len;
        return 1;
    }
}

// (path, lineno, idx) ordering — paths by surrogateescape codepoints.
static int dgrep_merge_less(const DgrepMergeCursor* x,
                            const DgrepMergeCursor* y) {
    int c;
    if (x->path_len == y->path_len &&
        memcmp(x->path, y->path, x->path_len) == 0)
        c = 0;
    else
        c = se_cmp(x->path, x->path_len, y->path, y->path_len);
    if (c) return c < 0;
    if (x->lineno != y->lineno) return x->lineno < y->lineno;
    return x->idx < y->idx;
}

extern "C" {

// Merge n_bufs pre-sorted mr-out buffers (concatenated in `data`,
// boundaries in buf_off[n_bufs + 1]) into display bytes: each record's
// line with its first '\t' replaced by ' ', plus '\n', in (path, line)
// order.  out needs up to buf_off[n_bufs] + n_bufs bytes: a buffer
// whose final line lacks a terminating '\n' gains one on output.
// Returns the output length, or -1 when any line is not grep-shaped
// (caller falls back to the Python merge).
int64_t dgrep_merge_display(const uint8_t* data, const int64_t* buf_off,
                            int32_t n_bufs, uint8_t* out) {
    std::vector<DgrepMergeCursor> cur;
    cur.reserve((size_t)n_bufs);
    for (int32_t i = 0; i < n_bufs; ++i) {
        DgrepMergeCursor c;
        c.pos = data + buf_off[i];
        c.end = data + buf_off[i + 1];
        c.idx = i;
        int r = dgrep_merge_advance(&c);
        if (r < 0) return -1;
        if (r) cur.push_back(c);
    }
    uint8_t* p = out;
    while (!cur.empty()) {
        size_t best = 0;
        for (size_t i = 1; i < cur.size(); ++i)
            if (dgrep_merge_less(&cur[i], &cur[best])) best = i;
        DgrepMergeCursor* c = &cur[best];
        memcpy(p, c->line, c->line_len);
        if (c->tab < c->line_len) p[c->tab] = ' ';
        p += c->line_len;
        *p++ = '\n';
        int r = dgrep_merge_advance(c);
        if (r < 0) return -1;
        if (!r) cur.erase(cur.begin() + (ptrdiff_t)best);
    }
    return (int64_t)(p - out);
}

}  // extern "C"

// --------------------------------------------------------------------------
// Trigram shard summaries (the shard-index tier): one pass over a shard's
// bytes ORs its case-folded trigram presence bloom into `bloom`.  Two bits
// per trigram position: the 24-bit folded trigram code is mixed with one
// 64-bit Fibonacci multiply and the low/high 32-bit halves index the bit
// array (bloom_bytes MUST be a power of two — the Python wrapper enforces
// it).  The numpy fallback (distributed_grep_tpu/index/summary.py) computes
// the IDENTICAL bits, so persisted summaries are interchangeable between
// builds; a query's required literal is absent whenever any of its folded
// trigrams' bit pairs is missing ("cannot match" — never the reverse).

static inline uint32_t dgrep_tg_fold(uint8_t c) {
    return (c >= 'A' && c <= 'Z') ? (uint32_t)c + 32u : (uint32_t)c;
}

extern "C" {

void dgrep_trigram_summary(const uint8_t* data, size_t len,
                           uint8_t* bloom, size_t bloom_bytes) {
    if (len < 3 || bloom_bytes == 0) return;
    const uint64_t mask = (uint64_t)bloom_bytes * 8u - 1u;
    uint32_t a = dgrep_tg_fold(data[0]);
    uint32_t b = dgrep_tg_fold(data[1]);
    for (size_t i = 2; i < len; ++i) {
        uint32_t c = dgrep_tg_fold(data[i]);
        uint64_t v = ((uint64_t)a << 16) | ((uint64_t)b << 8) | (uint64_t)c;
        uint64_t h = v * 0x9E3779B97F4A7C15ull;
        uint64_t h1 = h & mask;
        uint64_t h2 = (h >> 32) & mask;
        bloom[h1 >> 3] = (uint8_t)(bloom[h1 >> 3] | (1u << (h1 & 7u)));
        bloom[h2 >> 3] = (uint8_t)(bloom[h2 >> 3] | (1u << (h2 & 7u)));
        a = b;
        b = c;
    }
}

}  // extern "C"

// libdgrep — native host-side hot loops for distributed_grep_tpu.
//
// The reference implements its runtime in compiled Go; the TPU-native build
// keeps the runtime's hot host-side loops native too (the TPU compute path
// is JAX/XLA/Pallas; this library covers what runs on the host):
//
//   * fnv32a        — FNV-32a partition hash (reference: ihash,
//                     map_reduce/worker.go:13-17; partition = hash % nReduce,
//                     worker.go:89).
//   * newline_index — newline offset scan (memchr loop) used to slice match
//                     byte-offsets into grep line numbers without Python
//                     per-byte loops.
//   * literal_scan  — memmem-based literal substring scan emitting match end
//                     offsets; CPU fallback engine + oracle for kernels.
//   * dfa_scan      — table-driven DFA byte scan emitting accept offsets;
//                     the host-side oracle for the Pallas DFA kernel.
//
// Build: make -C native   (produces libdgrep.so; loaded via ctypes from
// distributed_grep_tpu/utils/native.py, with pure-Python fallbacks).

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

// FNV-32a over `len` bytes, masked to non-negative int32 like the reference
// does (worker.go:13-17 masks with 0x7fffffff).
uint32_t dgrep_fnv32a(const uint8_t* data, size_t len) {
    uint32_t h = 2166136261u;
    for (size_t i = 0; i < len; ++i) {
        h ^= data[i];
        h *= 16777619u;
    }
    return h & 0x7fffffffu;
}

// Write byte offsets of every '\n' into out (capacity max_out).
// Returns the total number of newlines found (may exceed max_out; caller
// re-calls with a bigger buffer in that case).
size_t dgrep_newline_index(const uint8_t* data, size_t len,
                           uint64_t* out, size_t max_out) {
    size_t count = 0;
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    while (p < end) {
        const uint8_t* nl = (const uint8_t*)memchr(p, '\n', (size_t)(end - p));
        if (!nl) break;
        if (count < max_out) out[count] = (uint64_t)(nl - data);
        ++count;
        p = nl + 1;
    }
    return count;
}

// Find end-offsets (offset of last byte + 1) of every occurrence of
// `needle` in `hay` (overlapping occurrences included, matching regex
// scan-all semantics). Returns total count; writes up to max_out offsets.
size_t dgrep_literal_scan(const uint8_t* hay, size_t hay_len,
                          const uint8_t* needle, size_t needle_len,
                          uint64_t* out, size_t max_out) {
    if (needle_len == 0 || needle_len > hay_len) return 0;
    size_t count = 0;
    const uint8_t* p = hay;
    const uint8_t* end = hay + hay_len;
    while (p + needle_len <= end) {
        const uint8_t* hit =
            (const uint8_t*)memmem(p, (size_t)(end - p), needle, needle_len);
        if (!hit) break;
        if (count < max_out)
            out[count] = (uint64_t)(hit - hay) + needle_len;
        ++count;
        p = hit + 1;  // overlapping matches
    }
    return count;
}

// Table-driven DFA scan. `table` is row-major [n_states][256] uint16 next
// states; `accept` is a per-state 0/1 byte map. Starts in `start_state`,
// feeds every byte, records offset i+1 whenever the post-transition state is
// accepting. Returns total accept count (writes up to max_out offsets) and
// stores the final state in *final_state (for cross-chunk state carry).
size_t dgrep_dfa_scan(const uint8_t* data, size_t len,
                      const uint16_t* table, const uint8_t* accept,
                      uint32_t start_state,
                      uint64_t* out, size_t max_out,
                      uint32_t* final_state) {
    uint32_t s = start_state;
    size_t count = 0;
    for (size_t i = 0; i < len; ++i) {
        s = table[((size_t)s << 8) | data[i]];
        if (accept[s]) {
            if (count < max_out) out[count] = (uint64_t)i + 1;
            ++count;
        }
    }
    if (final_state) *final_state = s;
    return count;
}

}  // extern "C"

#!/bin/sh
# Best-effort C++ static analysis over libdgrep.  Runs whichever of
# cppcheck / clang-tidy is installed and exits nonzero on findings; when
# neither binary exists it no-ops with exit 0 (CI containers without the
# tools must not fail the build — the Python-side `analyze` subcommand is
# the always-on layer; this is the extra native-side pass).
set -eu
cd "$(dirname "$0")"

ran=0
if command -v cppcheck >/dev/null 2>&1; then
    ran=1
    # --error-exitcode makes findings fail; style/perf classes included.
    cppcheck --std=c++17 --language=c++ \
        --enable=warning,performance,portability \
        --inline-suppr --error-exitcode=2 dgrep.cpp
fi
if command -v clang-tidy >/dev/null 2>&1; then
    ran=1
    clang-tidy dgrep.cpp --warnings-as-errors='*' -- -std=c++17 -x c++
fi
if [ "$ran" = 0 ]; then
    echo "native/lint.sh: cppcheck/clang-tidy not installed; skipping" >&2
fi

"""K concurrent tenants over one warm corpus: fused vs unfused daemon.

ISSUE 11's acceptance receipt: with cross-tenant scan fusion ON
(DGREP_SERVICE_FUSE=1, the default) K=4 co-running grep jobs over the
same corpus share ONE scan per map split; with it OFF each tenant pays
its own full scan.  This benchmark drives the REAL surface end to end —
ServiceServer HTTP API (POST /jobs, GET /jobs/<id>), one in-process
worker — and reports interleaved A/B medians (this box's background
load swings ~2x; single draws lie):

    python benchmarks/fused_tenants.py [--tenants 4] [--files 4]
        [--file-kb 32768] [--patterns 0] [--reps 5] [--check]

``--check`` additionally asserts the fused legs' outputs are
byte-identical to the unfused legs' (same pattern sets, same corpus —
the unfused daemon is the solo oracle).  Prints exactly ONE JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import string
import sys
import tempfile
import threading
import time
import urllib.request
from pathlib import Path

# Runnable as `python benchmarks/...` from anywhere: the repo root joins
# the FRONT of sys.path so the checkout being benchmarked always wins.
_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

# CPU-pinned (CLAUDE.md environment rules): ASSIGN, never setdefault,
# AND pop the axon plugin factory — backend discovery calls every
# registered factory even under jax_platforms=cpu, and a black-holed
# tunnel blocks that call forever (same as tests/conftest.py).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("DGREP_NO_CALIBRATE", "1")
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")


def _needles(tenant: int, k: int = 4) -> list[str]:
    return [f"needle{tenant}mark{i}x" for i in range(k)]


def _pattern_set(n: int, seed: int, tenant: int) -> list[str]:
    """A tenant's literal set: SELECTIVE queries (8-14 char random
    strings essentially never occur in random text — the log/code-search
    shape, where a query matches a small fraction of the corpus) plus a
    few planted needles so every tenant's output is non-trivial.  Dense
    queries are the anti-regime by construction: fusion trades K full
    scans for one union scan + K confirms over CANDIDATE lines only, so
    its win scales with query selectivity."""
    rng = random.Random(seed)
    out = set(_needles(tenant))
    while len(out) < n:
        out.add("".join(
            rng.choice(string.ascii_lowercase)
            for _ in range(rng.randint(8, 14))
        ))
    return sorted(out)


def _make_corpus(root: Path, n_files: int, file_kb: float, n_tenants: int,
                 seed: int = 7) -> list[str]:
    rng = random.Random(seed)
    words = ["".join(rng.choice(string.ascii_lowercase)
                     for _ in range(rng.randint(3, 9))) for _ in range(400)]
    planted = [n for t in range(n_tenants) for n in _needles(t)]
    files = []
    lineno = 0
    for i in range(n_files):
        p = root / f"in{i:03d}.txt"
        target = int(file_kb * 1024)
        parts = []
        size = 0
        while size < target:
            line = " ".join(rng.choice(words)
                            for _ in range(rng.randint(6, 14)))
            if lineno % 211 == 0:  # ~0.5% of lines carry some needle
                line += " " + planted[(lineno // 211) % len(planted)]
            line += "\n"
            lineno += 1
            parts.append(line)
            size += len(line)
        p.write_text("".join(parts))
        files.append(str(p))
    return files


def _http(method: str, url: str, body: bytes | None = None,
          timeout: float = 30.0) -> dict:
    req = urllib.request.Request(url, data=body, method=method)
    if body is not None:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tenants", type=int, default=4)
    # scan-dominated splits by default: fusion removes SCANS, not task
    # commits (each participant still pays its own exactly-once commit
    # protocol — ~10 ms of fsync-bound work per task on this box), so
    # many tiny splits measure the commit floor, not the fusion lever
    ap.add_argument("--files", type=int, default=4)
    ap.add_argument("--file-kb", type=float, default=32768)
    ap.add_argument("--patterns", type=int, default=0,
                    help="literal-set size per tenant; 0 (default) = one "
                         "selective REGEX per tenant — the common tenant "
                         "shape, where solo and union automata are both "
                         "cache-resident and fusion's K-fold scan saving "
                         "shows whole.  Large literal sets still win, but "
                         "less: the union's K-fold-larger AC table falls "
                         "out of L2 and gives part of the saving back")
    ap.add_argument("--reps", type=int, default=5,
                    help="A/B rep pairs; medians reported")
    ap.add_argument("--check", action="store_true",
                    help="assert fused outputs byte-identical to the "
                         "unfused legs' and exit 1 on speedup < 2x")
    args = ap.parse_args()

    from distributed_grep_tpu.runtime.service import GrepService, ServiceServer
    from distributed_grep_tpu.utils.config import JobConfig

    tmp = Path(tempfile.mkdtemp(prefix="dgrep-fused-bench-"))
    corpus_dir = tmp / "corpus"
    corpus_dir.mkdir()
    files = _make_corpus(corpus_dir, args.files, args.file_kb, args.tenants)
    total_mb = sum(os.path.getsize(f) for f in files) / 1e6
    if args.patterns:
        queries = [
            {"patterns": _pattern_set(args.patterns, seed=100 + t, tenant=t)}
            for t in range(args.tenants)
        ]
    else:
        # one selective class-bearing regex per tenant (a pure literal
        # would ride the solo memmem fast path and measure memmem-vs-DFA,
        # not fusion); it matches exactly that tenant's planted needles
        queries = [
            {"pattern": f"needle{t}mark[0-3]x"} for t in range(args.tenants)
        ]

    service = GrepService(
        work_root=tmp / "svc",
        max_jobs=max(4, args.tenants),
    )
    server = ServiceServer(service)
    server.start()
    service.start_local_workers(1)
    base = f"http://127.0.0.1:{server.port}"

    def run_leg(fused: bool) -> tuple[float, list[list[str]]]:
        os.environ["DGREP_SERVICE_FUSE"] = "1" if fused else "0"
        t0 = time.perf_counter()
        jids: list[str] = []
        for t in range(args.tenants):
            cfg = JobConfig(
                input_files=files,
                application="distributed_grep_tpu.apps.grep_tpu",
                app_options={**queries[t], "backend": "cpu"},
                n_reduce=1,
            )
            jids.append(_http(
                "POST", f"{base}/jobs",
                cfg.to_json().encode("utf-8"),
            )["job_id"])
        outs: list[list[str]] = [[] for _ in jids]
        pending = set(range(len(jids)))
        while pending:
            for i in list(pending):
                st = _http("GET", f"{base}/jobs/{jids[i]}")
                state = st.get("state")
                if state == "done":
                    outs[i] = sorted(st["outputs"])
                    pending.discard(i)
                elif state in ("failed", "cancelled"):
                    raise RuntimeError(f"job {jids[i]}: {st}")
            if pending:
                # gentle poll: this box has ONE core — a hot client poll
                # loop steals cycles from the worker it is timing
                time.sleep(0.05)
        return time.perf_counter() - t0, outs

    def read_outputs(paths: list[str]) -> list[bytes]:
        return [Path(p).read_bytes() for p in paths]

    fused_s: list[float] = []
    unfused_s: list[float] = []
    check = "skipped"
    try:
        # one unmeasured warmup pair: model cache + page cache settle
        run_leg(True)
        run_leg(False)
        for rep in range(args.reps):
            fa, fused_outs = run_leg(True)
            fb, unfused_outs = run_leg(False)
            fused_s.append(fa)
            unfused_s.append(fb)
            if args.check and rep == 0:
                for t in range(args.tenants):
                    if read_outputs(fused_outs[t]) != read_outputs(
                        unfused_outs[t]
                    ):
                        print(json.dumps({
                            "bench": "fused_tenants", "error":
                            f"tenant {t} fused != unfused outputs",
                        }))
                        return 1
                check = "ok"
        status = _http("GET", f"{base}/status")
    finally:
        server.shutdown()
        service.stop()

    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    fused_med, unfused_med = med(fused_s), med(unfused_s)
    speedup = unfused_med / fused_med if fused_med else 0.0
    out = {
        "bench": "fused_tenants",
        "tenants": args.tenants,
        "files": args.files,
        "corpus_mb": round(total_mb, 1),
        "patterns_per_tenant": args.patterns or "1 regex",
        "reps": args.reps,
        "fused_s": round(fused_med, 3),
        "unfused_s": round(unfused_med, 3),
        "aggregate_speedup": round(speedup, 2),
        "fused_s_all": [round(x, 3) for x in fused_s],
        "unfused_s_all": [round(x, 3) for x in unfused_s],
        "fusion": status.get("fusion", {}),
        "check": check,
    }
    print(json.dumps(out))
    if args.check and (check != "ok" or speedup < 2.0):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

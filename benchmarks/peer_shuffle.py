"""Peer-to-peer shuffle receipt: daemon data-plane bytes flat at N workers.

ISSUE 14's acceptance bar: a match-dense multi-worker HTTP service job
with peer shuffle ON completes byte-identical to the relay path while the
daemon's measured shuffle data-plane bytes drop to ~0 (metadata only).
On this 1-core box N workers cannot show N-fold wall clock — every
"worker" shares one CPU — so the BYTES counter is the honest local proof:
it measures exactly the coordinator-NIC traffic the star topology forced
and P2P removes.  Wall times are reported as interleaved A/B medians for
context, not as the claim.

    python benchmarks/peer_shuffle.py [--files 8] [--file-kb 512]
        [--reps 3] [--check]

Drives the REAL surface end to end per run: a fresh GrepService +
ServiceServer, two HTTP workers (ServiceHttpTransport) each with its own
PeerDataServer in peer mode (none in relay mode), one submit through
POST /jobs, daemon shuffle bytes read from the service counters that
also feed GET /status "shuffle" and the dgrep_daemon_shuffle_bytes
gauge.  Prints exactly ONE JSON line.

Real-cluster recipe (the number this box cannot give): run `dgrep serve
--workers 0` on one host, `dgrep worker --addr` on N others
(DGREP_PEER_SHUFFLE=1 vs 0), a match-dense `dgrep submit`, and compare
job wall + the daemon's `/metrics` dgrep_daemon_shuffle_bytes — on a
tunnel-era TPU pod pair it with `--timing slope` engine receipts.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import threading
import time
from pathlib import Path

_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

# CPU-pinned (CLAUDE.md environment rules): ASSIGN + pop the axon factory.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("DGREP_NO_CALIBRATE", "1")
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")

from distributed_grep_tpu.runtime.http_transport import (  # noqa: E402
    ServiceHttpTransport,
    client_call,
)
from distributed_grep_tpu.runtime.peer import PeerDataServer  # noqa: E402
from distributed_grep_tpu.runtime.service import (  # noqa: E402
    GrepService,
    ServiceServer,
)
from distributed_grep_tpu.runtime.worker import WorkerLoop  # noqa: E402
from distributed_grep_tpu.utils.config import JobConfig  # noqa: E402


def _build_corpus(root: Path, files: int, file_kb: int) -> list[Path]:
    """Match-dense text: every third line hits the pattern, so the
    shuffle carries real volume (the regime the star topology chokes
    on)."""
    root.mkdir(parents=True, exist_ok=True)
    out = []
    for i in range(files):
        p = root / f"dense{i:02d}.txt"
        lines = []
        j = 0
        size = 0
        target = file_kb * 1024
        while size < target:
            line = (f"line {j} of file {i} "
                    + ("needle haystack match" if j % 3 == 0 else "plain"))
            lines.append(line)
            size += len(line) + 1
            j += 1
        p.write_text("\n".join(lines) + "\n")
        out.append(p)
    return out


def _run_once(corpus: list[Path], tmp: Path, peer_on: bool, rep: int
              ) -> tuple[float, dict, dict[str, bytes]]:
    """(wall seconds, daemon shuffle stats, outputs-by-name)."""
    svc = GrepService(work_root=tmp / f"svc-{peer_on}-{rep}", resume=False)
    server = ServiceServer(svc)
    server.start()
    addr = f"127.0.0.1:{server.port}"
    peers, threads = [], []
    for _ in range(2):
        peer = PeerDataServer().start() if peer_on else None
        peers.append(peer)
        loop = WorkerLoop(
            ServiceHttpTransport(addr, rpc_timeout_s=15.0), app=None,
            peer=peer,
        )
        t = threading.Thread(target=loop.run, daemon=True)
        t.start()
        threads.append(t)
    cfg = JobConfig(
        input_files=[str(p) for p in corpus],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": "needle", "backend": "cpu"},
        n_reduce=2,
        work_dir="ignored",
    )
    t0 = time.perf_counter()
    jid = client_call(addr, "POST", "/jobs", cfg.to_json().encode(),
                      timeout=30.0)["job_id"]
    while True:
        st = client_call(addr, "GET", f"/jobs/{jid}", timeout=30.0)
        if st["state"] in ("done", "failed", "cancelled"):
            break
        time.sleep(0.02)
    wall = time.perf_counter() - t0
    if st["state"] != "done":
        raise RuntimeError(f"job ended {st['state']}: {st}")
    res = client_call(addr, "GET", f"/jobs/{jid}/result", timeout=30.0)
    outs = {}
    for p in res["outputs"]:
        outs[Path(p).name.split(".part.")[0]] = Path(p).read_bytes()
    stats = dict(svc._shuffle_stats)
    svc.stop()
    server.shutdown()
    for p in peers:
        if p is not None:
            p.close()
    for t in threads:
        t.join(timeout=10)
    return wall, stats, outs


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--files", type=int, default=8)
    ap.add_argument("--file-kb", type=int, default=512)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--check", action="store_true",
                    help="assert byte identity + peer-mode daemon "
                         "shuffle bytes == 0")
    args = ap.parse_args()

    tmp = Path(tempfile.mkdtemp(prefix="dgrep-peer-bench-"))
    corpus = _build_corpus(tmp / "corpus", args.files, args.file_kb)

    walls: dict[bool, list[float]] = {True: [], False: []}
    bytes_seen: dict[bool, list[int]] = {True: [], False: []}
    outs_ref: dict[bool, dict] = {}
    # interleaved A/B: this box's background load swings 2x, so modes
    # alternate within one window instead of running in blocks
    for rep in range(args.reps):
        for peer_on in (True, False):
            wall, stats, outs = _run_once(corpus, tmp, peer_on, rep)
            walls[peer_on].append(wall)
            bytes_seen[peer_on].append(stats["daemon_shuffle_bytes"])
            outs_ref.setdefault(peer_on, outs)

    identical = outs_ref[True] == outs_ref[False]
    result = {
        "bench": "peer_shuffle",
        "files": args.files,
        "file_kb": args.file_kb,
        "reps": args.reps,
        "workers": 2,
        "peer_wall_s_median": round(statistics.median(walls[True]), 4),
        "relay_wall_s_median": round(statistics.median(walls[False]), 4),
        "daemon_shuffle_bytes_peer": max(bytes_seen[True]),
        "daemon_shuffle_bytes_relay": min(bytes_seen[False]),
        "outputs_identical": identical,
        "note": ("1-core box: wall medians are context only — the "
                 "bytes-flat counter is the receipt; see the module "
                 "docstring for the real-cluster recipe"),
    }
    print(json.dumps(result, sort_keys=True))
    if args.check:
        assert identical, "peer vs relay outputs differ"
        assert max(bytes_seen[True]) == 0, \
            f"peer mode moved daemon shuffle bytes: {bytes_seen[True]}"
        assert min(bytes_seen[False]) > 0, "relay mode counted no bytes"
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
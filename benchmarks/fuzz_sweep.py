"""Wide differential fuzz sweep: re-run the test-suite fuzz generators over
ARBITRARY seed ranges (the checked-in suite pins small fixed ranges so CI
stays ~6 min; this driver is the long-haul version for soak sessions).

    python benchmarks/fuzz_sweep.py [--families regex,ignore_case,...]
                                    [--start 100] [--count 500]

Each family row prints pass/fail counts; any failure prints the seed and
re-raisable repro line and exits 1.  Runs on CPU (the tests' interpret-mode
kernels), no TPU required.
"""

from __future__ import annotations

import argparse
import inspect
import os
import signal as _signal
import sys
import traceback
from pathlib import Path

# CPU-pinned like tests/conftest.py — FORCED, not setdefault: the
# deployment environment ships JAX_PLATFORMS=axon globally, which a
# setdefault silently honors (observed: this script then runs every seed
# through the device tunnel until the tunnel drops mid-campaign).  The
# axon plugin factory is also deregistered: even under jax_platforms=cpu,
# backend discovery calls every registered factory, and a black-holed
# tunnel blocks that call indefinitely.
os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")

_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
sys.path.insert(0, str(_root))
sys.path.insert(0, str(_root / "tests"))


class _SeedTimeout(Exception):
    pass


def _seed_boom(sig, frame):
    raise _SeedTimeout


def _families():
    import test_fuzz_recall as fr
    import test_pairset as tp

    fams = {"pairset": tp.test_pairset_fuzz_engine_vs_oracle}
    # every seed-parametrized fuzz function in test_fuzz_recall joins the
    # sweep automatically (dedup by function identity)
    seen = {id(v) for v in fams.values()}
    for name in dir(fr):
        fn = getattr(fr, name)
        if name.startswith("test_fuzz") and callable(fn) and id(fn) not in seen:
            fams[name.removeprefix("test_fuzz_")] = fn
            seen.add(id(fn))
    return fams


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--families", default=None)
    ap.add_argument("--start", type=int, default=100)
    ap.add_argument("--count", type=int, default=200)
    args = ap.parse_args()

    fams = _families()
    if args.families:
        keep = set(args.families.split(","))
        fams = {k: v for k, v in fams.items() if k in keep}

    failures = 0
    for name, fn in sorted(fams.items()):
        params = list(inspect.signature(fn).parameters)
        if params != ["seed"]:
            print(f"{name}: skipped (needs fixtures: {params})")
            continue
        ok = skipped = timed_out = 0
        for seed in range(args.start, args.start + args.count):
            # Per-seed wall: a drawn pattern can be EXPONENTIAL for the
            # backtracking `re` oracle (observed: seed 1352's nested
            # quantifiers hung the oracle >50 min while the engine's
            # automata scanned it in 0.16 s — ReDoS immunity).  Such
            # seeds are recorded and skipped.  NOTE the mechanism only
            # interrupts pure-Python phases (SIGALRM handlers run between
            # bytecodes): a stall inside jitted/native code would still
            # hang the sweep — those have their own walls in the engine.
            old = _signal.signal(_signal.SIGALRM, _seed_boom)
            _signal.alarm(180)
            try:
                fn(seed)
                ok += 1
            except _SeedTimeout:
                timed_out += 1
                print(f"TIMEOUT {name} seed={seed} (>180s — exponential "
                      f"re-oracle pattern, or an engine stall: triage "
                      f"manually)", flush=True)
            except AssertionError:
                failures += 1
                print(f"FAIL {name} seed={seed}")
                traceback.print_exc(limit=3)
            except BaseException as e:  # pytest.Skipped is a BaseException
                if "skip" in type(e).__name__.lower():
                    skipped += 1  # ineligible draw (e.g. no Pallas mode)
                    continue
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    raise
                failures += 1
                print(f"ERROR {name} seed={seed}: {e!r}")
                traceback.print_exc(limit=3)
            finally:
                _signal.alarm(0)
                _signal.signal(_signal.SIGALRM, old)
        note = f" ({skipped} ineligible-draw skips)" if skipped else ""
        if timed_out:
            note += f" ({timed_out} oracle timeouts)"
        print(f"{name}: {ok}/{args.count} ok{note}")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Many-small-files sweep: packed cross-file batching vs per-file host scan.

The regime the headline configs never touch (BASELINE.json scans big
splits): a `grep -r`-shaped corpus of thousands of sub-megabyte files,
where dispatch overhead — not bandwidth — prices the work.  This sweep
measures both sides of ISSUE 3's acceptance bar:

* ``host``   — per-file ``engine.scan`` on the cpu backend (native
  scanners), one dispatch per file: the pre-batching story.
* ``packed`` — ``engine.scan_batch`` on the device backend: small files
  pack into DGREP_BATCH_BYTES windows and each window is ONE kernel
  dispatch (ops/layout.BatchPacker).

    python benchmarks/many_small_files.py [--files 2000] [--file-kb 32]
        [--pattern volcano | --set N] [--timing e2e|slope] [--check]

``--timing slope`` packs the whole corpus into one buffer and slope-times
the device-resident kernel (utils/slope.py via baseline_configs.slope_gbps)
— the honest per-chip number through a slow tunnel, where e2e wall time
measures the link, not the kernel.  DGREP_NO_CALIBRATE=1 is forced for
deterministic FDR plans.  Prints exactly one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

# Runnable as `python benchmarks/...` from anywhere: the repo root joins
# the FRONT of sys.path so the checkout being benchmarked always wins.
_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

os.environ.setdefault("DGREP_NO_CALIBRATE", "1")  # deterministic FDR plans

import numpy as np

from distributed_grep_tpu.ops.engine import GrepEngine

WORDS = (
    "the of and to in a is that for it as was with be by on not he this are "
    "at from or have an they which one you were all her she there would "
    "fff needle volcano anarchism philosophy wikipedia"
).split()


def synth_files(n_files: int, file_bytes: int, needles: list[bytes],
                seed: int = 9) -> list[tuple[str, bytes]]:
    """English-like filler files; ~1 in 8 carries an injected needle (the
    grep -r shape: most files miss, some hit)."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n_files):
        lines, n = [], 0
        while n < file_bytes:
            k = int(rng.integers(3, 12))
            line = b" ".join(
                WORDS[int(rng.integers(0, len(WORDS)))].encode()
                for _ in range(k)
            )
            lines.append(line)
            n += len(line) + 1
        blob = b"\n".join(lines)[:file_bytes]
        if i % 8 == 0 and needles:
            nd = needles[int(rng.integers(0, len(needles)))]
            pos = int(rng.integers(0, max(1, len(blob) - len(nd) - 1)))
            blob = blob[:pos] + nd + blob[pos + len(nd):]
        out.append((f"f{i:05d}", blob))
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--files", type=int, default=2000)
    ap.add_argument("--file-kb", type=float, default=32)
    ap.add_argument("--pattern", default="volcano")
    ap.add_argument("--set", type=int, default=0, metavar="N",
                    help="use an N-literal pattern set (FDR path) instead "
                         "of the single pattern")
    ap.add_argument("--batch-mb", type=float, default=32)
    ap.add_argument("--timing", default="e2e", choices=["e2e", "slope"],
                    help="e2e: scan_batch wall incl. transfers; slope: "
                         "device-resident chained passes over the packed "
                         "layout (slow-link environments)")
    ap.add_argument("--check", action="store_true",
                    help="assert packed per-file lines == host per-file")
    args = ap.parse_args()

    file_bytes = int(args.file_kb * 1024)
    patterns = None
    pattern = args.pattern
    if args.set:
        rng = np.random.default_rng(5)
        pats = {args.pattern}
        while len(pats) < args.set:
            k = int(rng.integers(5, 10))
            pats.add("".join(chr(c) for c in rng.integers(97, 123, size=k)))
        patterns, pattern = sorted(pats), None
        needles = [p.encode() for p in patterns[:20]]
    else:
        needles = [pattern.encode()]
    files = synth_files(args.files, file_bytes, needles)
    total = sum(len(b) for _, b in files)
    out: dict = {
        "bench": "many_small_files",
        "files": args.files,
        "file_bytes": file_bytes,
        "bytes": total,
        "pattern": pattern or f"<set of {len(patterns)}>",
    }

    # --- host leg: per-file scans, one dispatch per file -------------------
    host = GrepEngine(pattern, patterns=patterns, backend="cpu")
    host_results = []
    t0 = time.perf_counter()
    for name, blob in files:
        host_results.append((name, host.scan(blob)))
    host_s = time.perf_counter() - t0
    out["host_gbps"] = round(total / 1e9 / host_s, 3)
    out["dispatches_host"] = args.files

    # --- packed leg: scan_batch on the device engine -----------------------
    eng = GrepEngine(
        pattern, patterns=patterns, backend="device",
        batch_bytes=int(args.batch_mb * (1 << 20)),
    )
    t0 = time.perf_counter()
    packed_results = eng.scan_batch(files)
    warm_s = time.perf_counter() - t0  # includes jit compiles
    st = dict(eng.stats)
    out["mode"] = eng.mode
    out["batched_files"] = st.get("batched_files", 0)
    out["dispatches_packed"] = (
        st.get("batch_dispatches", 0) + st.get("solo_dispatches", 0)
    )
    out["dispatches_saved"] = st.get("dispatches_saved", 0)
    out["batch_fill_ratio"] = st.get("batch_fill_ratio", 0.0)

    if args.timing == "slope":
        # Device-resident kernel throughput over the PACKED layout: pack
        # the whole corpus into one buffer and slope-time it (chained
        # i-dependent windows inside one jit — utils/slope.py via the
        # baseline suite's per-mode setup).
        sys.path.insert(0, str(_root / "benchmarks"))
        from baseline_configs import slope_gbps

        from distributed_grep_tpu.ops.layout import BatchPacker

        packer = BatchPacker(total + args.files + 1)
        for name, blob in files:
            packer.add(name, blob)
        packed_all = packer.pack().data
        got = slope_gbps(eng, packed_all)
        if got is None:
            out["error"] = f"no device slope path for mode {eng.mode}"
        else:
            gbps, label = got
            out["packed_gbps"] = round(gbps, 3)
            out["engine"] = label
            out["timing"] = "slope(device-resident,packed)"
    else:
        # warmed rescan: the jit specializations exist now, so this is the
        # steady-state number (the first pass is reported as compile_s)
        t0 = time.perf_counter()
        packed_results = eng.scan_batch(files)
        dt = time.perf_counter() - t0
        out["packed_gbps"] = round(total / 1e9 / dt, 3)
        out["timing"] = "e2e"
        out["compile_s"] = round(warm_s - dt, 2)
    if out.get("packed_gbps") and out.get("host_gbps"):
        out["speedup_vs_host"] = round(out["packed_gbps"] / out["host_gbps"], 2)

    if args.check:
        mism = []
        hr = dict(host_results)
        for name, res in packed_results:
            want = hr[name].matched_lines
            if not np.array_equal(res.matched_lines, want):
                mism.append(name)
        out["check"] = "ok" if not mism else f"MISMATCH {mism[:5]}"
        out["matched_lines"] = int(
            sum(r.n_matches for _, r in packed_results)
        )
    print(json.dumps(out), flush=True)
    return 0 if "error" not in out and "MISMATCH" not in str(out.get("check", "")) else 1


if __name__ == "__main__":
    sys.exit(main())

"""Query-result cache against the grep service: a repeated query over
unchanged inputs answers from stored per-split results in O(ms), and a
one-file append re-scans exactly one split.

ISSUE 18's acceptance bar: the warm full hit must beat the warm
UNCACHED scan (model cache hot, result tier off) by >= 10x, with
collated outputs byte-identical across hit / incremental / miss.

    python benchmarks/result_cache.py [--files 24] [--file-mb 1]
        [--reps 3] [--check]

Drives the REAL surface end to end: two ServiceServer HTTP daemons over
separate work roots — one with the result tier on, one with
DGREP_RESULT_CACHE=0 (the store is constructed at daemon start, so the
off leg needs its own daemon) — each with one in-process worker,
submits INTERLEAVED A/B (this box's background load swings single draws
±2x; medians over alternating reps are the honest comparison).  Output
comparison is COLLATED (sorted merged record lines): a cached job's
on-disk layout legitimately differs from a scanned job's.  Prints
exactly ONE JSON line.  ``--check`` exits 1 unless all legs are
byte-identical, the daemon reports the expected hits, AND the warm-hit
speedup clears 10x.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

# CPU-pinned (CLAUDE.md environment rules): ASSIGN, never setdefault — and
# pop the axon plugin factory (backend discovery calls every registered
# factory even under jax_platforms=cpu).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("DGREP_NO_CALIBRATE", "1")
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

WORDS = (
    "the of and to in a is that for it as was with be by on not he this "
    "are at from or have an they which one you were all her she there "
    "would filler wikipedia philosophy"
).split()


def write_corpus(root: Path, n_files: int, file_bytes: int,
                 seed: int = 9) -> list[Path]:
    rng = np.random.default_rng(seed)
    paths = []
    for i in range(n_files):
        lines, n = [], 0
        while n < file_bytes:
            k = int(rng.integers(3, 12))
            line = b" ".join(
                WORDS[int(rng.integers(0, len(WORDS)))].encode()
                for _ in range(k)
            )
            lines.append(line)
            n += len(line) + 1
        blob = b"\n".join(lines)[:file_bytes - 1] + b"\n"
        p = root / f"f{i:05d}.txt"
        p.write_bytes(blob)
        paths.append(p)
    return paths


def collate(paths: list[str]) -> bytes:
    """Layout-independent record comparison: merged, sorted lines."""
    lines: list[bytes] = []
    for p in sorted(paths):
        with open(p, "rb") as f:
            lines.extend(
                ln for ln in f.read().splitlines(keepends=True)
                if ln.strip()
            )
    lines.sort()
    return b"".join(lines)


class Daemon:
    def __init__(self, work_root: Path, cached: bool):
        from distributed_grep_tpu.runtime.service import (
            GrepService,
            ServiceServer,
        )

        prev = os.environ.pop("DGREP_RESULT_CACHE", None)
        if not cached:
            os.environ["DGREP_RESULT_CACHE"] = "0"
        try:
            self.service = GrepService(work_root=work_root)
        finally:
            os.environ.pop("DGREP_RESULT_CACHE", None)
            if prev is not None:
                os.environ["DGREP_RESULT_CACHE"] = prev
        self.server = ServiceServer(self.service)
        self.server.start()
        self.service.start_local_workers(1)
        self.base = f"http://127.0.0.1:{self.server.port}"

    def call(self, method: str, path: str, body: bytes | None = None):
        req = urllib.request.Request(f"{self.base}{path}", data=body,
                                     method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=600) as r:
            return json.loads(r.read())

    def submit_and_wait(self, cfg_json: bytes) -> tuple[float, bytes]:
        t0 = time.perf_counter()
        job_id = self.call("POST", "/jobs", cfg_json)["job_id"]
        while True:
            st = self.call("GET", f"/jobs/{job_id}")
            if st["state"] in ("done", "failed", "cancelled"):
                break
            time.sleep(0.005)
        dt = time.perf_counter() - t0
        if st["state"] != "done":
            raise RuntimeError(f"job {job_id} ended {st['state']}: {st}")
        res = self.call("GET", f"/jobs/{job_id}/result")
        return dt, collate(res.get("outputs", []))

    def stop(self):
        self.service.stop()
        self.server.shutdown()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--files", type=int, default=32)
    ap.add_argument("--file-mb", type=float, default=1.0)
    ap.add_argument("--pattern", default="wikipedia philosophy",
                    help="selective phrase whose WORDS are in every "
                         "shard: the index tier prunes nothing (blooms "
                         "all say maybe) and the cached result blobs "
                         "stay small — the hit measures routing, not "
                         "match-dense materialization")
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved A/B reps; MEDIANS reported")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless outputs identical, hits "
                         "reported, and warm-hit speedup >= 10x")
    args = ap.parse_args()

    from distributed_grep_tpu.utils.config import JobConfig

    root = Path(tempfile.mkdtemp(prefix="dgrep-result-cache-"))
    (root / "in").mkdir()
    file_bytes = int(args.file_mb * (1 << 20))
    paths = write_corpus(root / "in", args.files, file_bytes)
    total = sum(p.stat().st_size for p in paths)

    cfg_json = JobConfig(
        input_files=[str(p) for p in paths],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": args.pattern, "backend": "cpu"},
        n_reduce=2,
        journal=False,
    ).to_json().encode("utf-8")

    on = Daemon(root / "svc-on", cached=True)
    off = Daemon(root / "svc-off", cached=False)
    try:
        # warm-up: one pass each — seeds the result store on the cached
        # daemon and the compiled-model cache on both, so the A/B below
        # measures warm hit vs warm scan, not first-compile
        _, out_seed = on.submit_and_wait(cfg_json)
        off.submit_and_wait(cfg_json)

        hit_t: list[float] = []
        scan_t: list[float] = []
        outs: dict[str, bytes] = {}
        for _ in range(max(1, args.reps)):
            dt, out = on.submit_and_wait(cfg_json)
            hit_t.append(dt)
            outs["hit"] = out
            dt, out = off.submit_and_wait(cfg_json)
            scan_t.append(dt)
            outs["scan"] = out

        # incremental re-query: append ONE needle line to one file —
        # exactly one split drifts; the cached daemon re-scans only it
        needle = f"{args.pattern} zzyzxappended"
        with open(paths[0], "a") as f:
            f.write(needle + "\n")
        inc_t, out_inc = on.submit_and_wait(cfg_json)
        _, out_inc_oracle = off.submit_and_wait(cfg_json)

        status = on.call("GET", "/status")
    finally:
        on.stop()
        off.stop()

    med_hit = statistics.median(hit_t)
    med_scan = statistics.median(scan_t)
    speedup = med_scan / med_hit if med_hit else 0.0
    rc = status.get("result_cache", {})
    identical = (
        outs["hit"] == outs["scan"] == out_seed
        and out_inc == out_inc_oracle
        and needle.encode() in out_inc
    )
    out = {
        "bench": "result_cache",
        "files": args.files,
        "bytes": total,
        "backend": jax.default_backend(),
        "reps": args.reps,
        "warm_hit_s": round(med_hit, 4),
        "warm_scan_s": round(med_scan, 4),
        "hit_speedup": round(speedup, 3),
        "incremental_s": round(inc_t, 4),
        "result_cache": rc,
    }
    hits_ok = rc.get("result_hits", 0) >= max(1, args.reps)
    if args.check:
        out["check"] = "ok" if (identical and hits_ok) else "MISMATCH"

    print(json.dumps(out), flush=True)  # exactly one JSON line
    ok = identical and (not args.check or (hits_ok and speedup >= 10.0))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""On-device kernel comparison: GB/s per engine mode via the slope harness.

Times each scan engine (pallas shift-and, XLA shift-and, XLA DFA, k-stride
DFA, Aho-Corasick banks) on the same synthetic corpus, printing one JSON
line per engine.  Used to direct kernel optimisation work — the e2e config
suite mixes in host-link costs that a tunneled device distorts.

    python benchmarks/kernel_compare.py [--size-mb 64] [--engines dfa,stride4]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

# Runnable as `python benchmarks/...` / `python bench.py` from anywhere:
# the repo root joins the FRONT of sys.path unconditionally, so the
# checkout being benchmarked always wins over any installed copy of the
# package.  (Repeated per script by necessity — a shared helper could not
# be imported before the path is fixed.)
_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

import numpy as np


def make_corpus(n: int) -> bytes:
    rng = np.random.default_rng(0)
    data = rng.integers(32, 127, size=n, dtype=np.uint8)
    data[rng.integers(0, n, size=n // 80)] = 0x0A
    needle = np.frombuffer(b"needle", np.uint8)
    for p in rng.integers(0, n - 16, size=1000):
        data[p : p + len(needle)] = needle
    return data.tobytes()


def _layout(data: bytes, *, lane_multiple=8, chunk_multiple=512, target_lanes=8192):
    import jax
    import jax.numpy as jnp

    from distributed_grep_tpu.ops import layout as layout_mod

    lay = layout_mod.choose_layout(
        len(data),
        target_lanes=target_lanes,
        min_chunk=512,
        lane_multiple=lane_multiple,
        chunk_multiple=chunk_multiple,
    )
    arr = layout_mod.to_device_array(data, lay)
    pad_rows = 512
    pad = np.full((pad_rows, arr.shape[1]), 0x0A, dtype=np.uint8)
    dev = jax.device_put(jnp.asarray(np.concatenate([arr, pad], axis=0)))
    return dev, lay, pad_rows


def bench_pallas(data):
    from distributed_grep_tpu.models.shift_and import try_compile_shift_and
    from distributed_grep_tpu.utils.slope import pallas_shift_and_setup, slope_per_pass

    model = try_compile_shift_and("needle")
    dev, chunk, pad_rows, scan = pallas_shift_and_setup(data, model)
    per_pass, _ = slope_per_pass(dev, chunk, pad_rows, scan, r1=2, r2=10)
    return len(data) / 1e9 / per_pass


def bench_nfa(data, pattern="nee(dle|t)"):
    from distributed_grep_tpu.models.nfa import try_compile_glushkov
    from distributed_grep_tpu.ops import pallas_nfa
    from distributed_grep_tpu.utils.slope import pallas_nfa_setup, slope_per_pass

    model = try_compile_glushkov(pattern)
    assert model is not None and pallas_nfa.eligible(model)
    dev, chunk, pad_rows, scan = pallas_nfa_setup(data, model)
    per_pass, _ = slope_per_pass(dev, chunk, pad_rows, scan, r1=8, r2=64)
    return len(data) / 1e9 / per_pass


def bench_xla_shift_and(data):
    import jax.numpy as jnp

    from distributed_grep_tpu.models.shift_and import try_compile_shift_and
    from distributed_grep_tpu.ops import scan_jnp
    from distributed_grep_tpu.utils.slope import slope_per_pass

    model = try_compile_shift_and("needle")
    dev, lay, pad_rows = _layout(data)
    b_table = jnp.asarray(model.b_table)
    mb = jnp.uint32(model.match_bit)

    def scan(win):
        return scan_jnp._shift_and_core(win, b_table, mb)

    per_pass, _ = slope_per_pass(dev, lay.chunk, pad_rows, scan, r1=2, r2=6)
    return len(data) / 1e9 / per_pass


def _dfa_closure(table):
    import jax.numpy as jnp

    from distributed_grep_tpu.ops import scan_jnp

    trans_flat = jnp.asarray(table.trans.astype(np.int32).reshape(-1))
    byte_cls = jnp.asarray(table.byte_to_cls.astype(np.int32))
    accept = jnp.asarray(table.accept)
    accept_eol = jnp.asarray(table.accept_eol)

    def scan(win):
        return scan_jnp._dfa_scan_core(
            win, trans_flat, byte_cls, accept, accept_eol,
            jnp.int32(table.start), table.n_classes,
        )

    return scan


def bench_dfa(data, pattern="nee(dle|t)"):
    from distributed_grep_tpu.models.dfa import compile_dfa
    from distributed_grep_tpu.utils.slope import slope_per_pass

    table = compile_dfa(pattern)
    dev, lay, pad_rows = _layout(data)
    per_pass, _ = slope_per_pass(dev, lay.chunk, pad_rows, _dfa_closure(table), r1=2, r2=6)
    return len(data) / 1e9 / per_pass


def bench_stride(data, k, pattern="nee(dle|t)"):
    import jax.numpy as jnp

    from distributed_grep_tpu.models.dfa import build_stride_table, compile_dfa
    from distributed_grep_tpu.ops import scan_jnp
    from distributed_grep_tpu.utils.slope import slope_per_pass

    st = build_stride_table(compile_dfa(pattern), k)
    dev, lay, pad_rows = _layout(data, chunk_multiple=512)
    trans = jnp.asarray(st.trans_k.reshape(-1))
    byte_cls = jnp.asarray(st.byte_to_cls.astype(np.int32))

    def scan(win):
        return scan_jnp._dfa_stride_core(
            win, trans, byte_cls, jnp.int32(st.start), st.k, st.n_classes
        )

    per_pass, _ = slope_per_pass(dev, lay.chunk, pad_rows, scan, r1=2, r2=6)
    return len(data) / 1e9 / per_pass


def bench_pairset(data):
    """Exact 1-2-byte set kernel (models/pairset): 4 gathers/byte, no
    confirm — the round-4 device engine for the sets FDR cannot host."""
    from distributed_grep_tpu.models.pairset import compile_pairset
    from distributed_grep_tpu.utils.slope import pallas_pairset_setup, slope_per_pass

    model = compile_pairset([b"ne", b"ed", b"zq", b"9!", b"x"])
    dev, chunk, pad_rows, scan = pallas_pairset_setup(data, model)
    per_pass, _ = slope_per_pass(dev, chunk, pad_rows, scan, r1=8, r2=64)
    return len(data) / 1e9 / per_pass


def bench_mxu_dot(data):
    """The MXU shared-contraction formulation's honest cost (VERDICT r3
    item 7): per byte, one-hot(byte) (128,256) int8 @ membership (256,128)
    on the MXU — 32768 MACs/byte (8192 at K=32 columns, but the MXU tile
    pads K to 128 anyway).  Scan semantics (pair chaining, bit packing)
    are ELIDED, so this measures an UPPER BOUND on what any one-hot-dot
    membership engine could reach; compare against `pairset` (the 4-gather
    VPU factorization, exact, with full scan semantics)."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from distributed_grep_tpu.ops.pallas_scan import (
        CHUNK_BLOCK_WORDS, LANE_COLS, SUBLANES,
    )
    from distributed_grep_tpu.utils.slope import (
        _pallas_device_setup, slope_per_pass,
    )

    rng = np.random.default_rng(0)
    member = jnp.asarray(
        rng.integers(0, 2, size=(256, 128), dtype=np.int8)
    )
    steps = 32 * CHUNK_BLOCK_WORDS

    def kernel(data_ref, m_ref, out_ref):
        ci = pl.program_id(1)

        @pl.when(ci == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        def body(t, acc):
            def sub(s, a2):
                row = data_ref[t, s].astype(jnp.int32)  # (128,) bytes
                oh = (
                    row[:, None]
                    == jax.lax.broadcasted_iota(jnp.int32, (LANE_COLS, 256), 1)
                ).astype(jnp.int8)
                d = jax.lax.dot_general(
                    oh, m_ref[...], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32,
                )
                return a2 + d
            return jax.lax.fori_loop(0, SUBLANES, sub, acc)

        out_ref[...] += jax.lax.fori_loop(
            0, steps, body, jnp.zeros((LANE_COLS, LANE_COLS), jnp.int32)
        )

    @functools.partial(jax.jit, static_argnames=("chunk", "lane_blocks"))
    def probe(dat, memb, *, chunk, lane_blocks):
        return pl.pallas_call(
            kernel,
            grid=(lane_blocks, chunk // steps),
            in_specs=[
                pl.BlockSpec((steps, SUBLANES, LANE_COLS),
                             lambda li, ci: (ci, li, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((256, LANE_COLS), lambda li, ci: (0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((LANE_COLS, LANE_COLS),
                                   lambda li, ci: (0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((LANE_COLS, LANE_COLS), jnp.int32),
        )(dat, memb)

    dev, lay, lane_blocks, pad_rows = _pallas_device_setup(data, 8192)

    def scan(win):
        return probe(win, member, chunk=lay.chunk, lane_blocks=lane_blocks)

    try:
        per_pass, _ = slope_per_pass(dev, lay.chunk, pad_rows, scan, r1=2, r2=6)
        return len(data) / 1e9 / per_pass
    except Exception as e:  # noqa: BLE001 — Mosaic inexpressibility IS a result
        # Measured closure (2026-07-30, v5e): Mosaic rejects the per-lane
        # one-hot layout ("cannot statically prove that index in dimension
        # 1 is a multiple of 8" — the (lane, 256) one-hot needs
        # sublane-granularity loads no TPU vreg layout provides), so the
        # in-kernel formulation cannot even compile.  Fall back to the
        # XLA-materialized form (the round-2 result: intermediates round-
        # trip HBM) on a 4 MB window so the entry still reports a measured
        # number for the comparison table.
        print(f"mxu_dot in-kernel: {type(e).__name__} (Mosaic layout); "
              f"measuring XLA-materialized form", file=sys.stderr)
        small = data[: 4 * 1024 * 1024]
        dev2, lay2, _, pad2 = _pallas_device_setup(small, 8192)

        @jax.jit
        def xla_scan(win):
            flat = win.reshape(-1).astype(jnp.int32)
            oh = (flat[:, None] == jnp.arange(256, dtype=jnp.int32)).astype(jnp.int8)
            d = jax.lax.dot_general(
                oh, member, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            # small deterministic scalar: the slope harness accumulates
            # per-pass results in int32, so a raw .sum() would overflow
            return jnp.count_nonzero(d) % jnp.int32(1021)

        per_pass, _ = slope_per_pass(dev2, lay2.chunk, pad2, xla_scan, r1=2, r2=6)
        return len(small) / 1e9 / per_pass


def bench_native_mt(data):
    """Host-side reference point for the short-set engines: the native MT
    DFA scanner over the same 5-member set's AC automaton."""
    import time

    from distributed_grep_tpu.models.aho import compile_aho_corasick
    from distributed_grep_tpu.utils.native import dfa_scan_mt

    t = compile_aho_corasick([b"ne", b"ed", b"zq", b"9!", b"x"])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        dfa_scan_mt(data, t.full_table(), t.accept, t.start)
        best = min(best, time.perf_counter() - t0)
    return len(data) / 1e9 / best


def bench_aho(data, n_patterns=256):
    from distributed_grep_tpu.models.aho import compile_aho_corasick_banks
    from distributed_grep_tpu.utils.slope import slope_per_pass

    rng = np.random.default_rng(1)
    pats = ["needle"] + [
        "".join(chr(c) for c in rng.integers(97, 123, size=int(rng.integers(5, 12))))
        for _ in range(n_patterns - 1)
    ]
    banks = compile_aho_corasick_banks(pats)
    dev, lay, pad_rows = _layout(data)
    total = 0.0
    for table in banks:
        scan = _dfa_closure(table)
        per_pass, _ = slope_per_pass(dev, lay.chunk, pad_rows, scan, r1=2, r2=6)
        total += per_pass
    return len(data) / 1e9 / total, len(banks)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=int, default=64)
    ap.add_argument("--engines", default="pallas,xla_sa,dfa,stride2,stride4,aho256")
    args = ap.parse_args()
    data = make_corpus(args.size_mb * 1024 * 1024)
    engines = args.engines.split(",")
    import jax

    print(f"backend={jax.default_backend()}", file=sys.stderr)
    for eng in engines:
        try:
            extra = {}
            if eng == "pallas":
                v = bench_pallas(data)
            elif eng == "nfa":
                v = bench_nfa(data)
            elif eng == "nfa_alt8":
                v = bench_nfa(data, "(volcano|anarchy|physics|quantum|needle|breadth|journal|mineral)")
            elif eng == "xla_sa":
                v = bench_xla_shift_and(data)
            elif eng == "dfa":
                v = bench_dfa(data)
            elif eng == "pairset":
                v = bench_pairset(data)
            elif eng == "mxu_dot":
                v = bench_mxu_dot(data)
            elif eng == "native_mt":
                v = bench_native_mt(data)
            elif eng.startswith("stride"):
                v = bench_stride(data, int(eng[len("stride"):]))
            elif eng.startswith("aho"):
                v, nb = bench_aho(data, int(eng[len("aho"):]))
                extra["banks"] = nb
            else:
                raise ValueError(f"unknown engine {eng}")
            print(json.dumps({"engine": eng, "value": round(v, 3), "unit": "GB/s", **extra}))
        except Exception as e:  # noqa: BLE001
            print(json.dumps({"engine": eng, "error": f"{type(e).__name__}: {e}"}))
        sys.stdout.flush()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Cold-vs-warm submit latency against the grep-as-a-service daemon.

ISSUE 6's acceptance bar: a repeated pattern's second submit to a running
daemon must be strictly faster than the first on this CPU box, because the
cross-job compiled-model cache (ops/engine.cached_engine) skips engine
construction — off-chip, AC-bank/model compile for a large literal set
dominates a small job's wall, so the effect is CPU-measurable (on a real
chip the same cache additionally skips the ~20-40 s first XLA/Mosaic
compile per fresh shape key).

    python benchmarks/service_warm.py [--patterns 1500] [--warm-reps 3]
        [--check]
    python benchmarks/service_warm.py --corpus-warm [--files 32]
        [--file-kb 128] [--check]

Drives the REAL surface end to end: ServiceServer HTTP API (POST /jobs,
GET /jobs/<id>), one in-process worker (deterministic warm path: the one
worker's second configure must come from the cache, not a sibling's).
Submits alternate between two equal-sized pattern sets A/B so every warm
submit pays a real reconfigure THROUGH the cache (the app-level same-config
short-circuit cannot answer it).  Prints exactly ONE JSON line.

``--corpus-warm`` (round 7) separates the TWO caches' contributions over
a multi-file corpus on the device backend: cold (both miss), corpus-warm
only (a FRESH literal set per submit — the model cache cannot answer, the
resident shards do), model-warm only (a known set, the corpus cache
cleared before each submit — the data path is paid again), and both warm
(the repeat-query steady state).  The in-process worker shares this
process, so the per-leg cache clears reach the worker's engines directly.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import string
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

# Runnable as `python benchmarks/...` from anywhere: the repo root joins
# the FRONT of sys.path so the checkout being benchmarked always wins.
_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

# CPU-pinned (CLAUDE.md environment rules): ASSIGN, never setdefault — a
# tunneled-TPU default backend would price the submit path with device
# dispatch latency, and this benchmark measures host-side model build —
# AND pop the axon plugin factory: backend discovery calls every
# registered factory even under jax_platforms=cpu, and a black-holed
# tunnel blocks that call forever (same as tests/conftest.py).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("DGREP_NO_CALIBRATE", "1")
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")


def _pattern_set(n: int, seed: int) -> list[str]:
    rng = random.Random(seed)
    out = set()
    while len(out) < n:
        out.add("".join(
            rng.choice(string.ascii_lowercase)
            for _ in range(rng.randint(5, 12))
        ))
    return sorted(out)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--patterns", type=int, default=1500,
                    help="literal-set size per job (model build dominates)")
    ap.add_argument("--warm-reps", type=int, default=3,
                    help="warm submits per set; the MIN is reported")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless warm < cold")
    ap.add_argument("--corpus-warm", action="store_true",
                    help="4-leg mode over a multi-file corpus: separate "
                         "the model cache's and the corpus cache's "
                         "contributions (cold / corpus-warm only / "
                         "model-warm only / both)")
    ap.add_argument("--files", type=int, default=32,
                    help="corpus files (--corpus-warm mode)")
    ap.add_argument("--file-kb", type=float, default=128,
                    help="KB per corpus file (--corpus-warm mode)")
    args = ap.parse_args()

    from distributed_grep_tpu.runtime.service import GrepService, ServiceServer
    from distributed_grep_tpu.utils.config import JobConfig

    root = Path(tempfile.mkdtemp(prefix="dgrep-svc-warm-"))
    corpus = root / "corpus.txt"
    corpus.write_bytes(b"".join(
        f"line {i} with some words in it\n".encode() for i in range(2000)
    ))

    service = GrepService(work_root=root / "svc")
    server = ServiceServer(service)
    server.start()
    service.start_local_workers(1)
    base = f"http://127.0.0.1:{server.port}"

    def call(method: str, path: str, body: bytes | None = None) -> dict:
        req = urllib.request.Request(f"{base}{path}", data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    def _submit(cfg: JobConfig) -> float:
        t0 = time.perf_counter()
        job_id = call("POST", "/jobs", cfg.to_json().encode("utf-8"))["job_id"]
        while True:
            st = call("GET", f"/jobs/{job_id}")
            if st["state"] in ("done", "failed", "cancelled"):
                break
            time.sleep(0.01)
        dt = time.perf_counter() - t0
        if st["state"] != "done":
            raise RuntimeError(f"job {job_id} ended {st['state']}: {st}")
        return dt

    def submit_and_wait(patterns: list[str]) -> float:
        return _submit(JobConfig(
            input_files=[str(corpus)],
            application="distributed_grep_tpu.apps.grep_tpu",
            app_options={"patterns": patterns, "backend": "cpu"},
            n_reduce=2,
            journal=False,
        ))

    set_a = _pattern_set(args.patterns, seed=1)
    set_b = _pattern_set(args.patterns, seed=2)

    if args.corpus_warm:
        # 4-leg cache attribution (round 7): the device corpus cache
        # (ops/layout.CorpusCache) vs the compiled-model cache, over a
        # multi-file corpus on the device backend.  The in-process
        # worker's engines live in THIS process, so per-leg clears of
        # either cache reach them directly.
        from distributed_grep_tpu.ops.layout import corpus_cache_clear

        files_dir = root / "in"
        files_dir.mkdir()
        file_bytes = int(args.file_kb * 1024)
        paths = []
        for i in range(args.files):
            blob = b"".join(
                (b"a volcano erupts here\n" if j % 97 == 0
                 else b"filler line %d of file %d\n" % (j, i))
                for j in range(max(1, file_bytes // 24))
            )
            p = files_dir / f"f{i:04d}.txt"
            p.write_bytes(blob)
            paths.append(p)
        total = sum(p.stat().st_size for p in paths)

        def submit_corpus(patterns: list[str]) -> float:
            return _submit(JobConfig(
                input_files=[str(p) for p in paths],
                application="distributed_grep_tpu.apps.grep_tpu",
                # "volcano" guarantees matches; the literal set sizes the
                # model build (what the model-cache legs amortize)
                app_options={"patterns": patterns + ["volcano"],
                             "backend": "device",
                             "corpus_bytes": 1 << 30},
                batch_bytes=32 << 20,
                n_reduce=2,
                journal=False,
            ))

        reps = max(1, args.warm_reps)
        cold_s = submit_corpus(set_a)  # both caches miss
        # corpus-warm ONLY: a fresh literal set per submit — the model
        # cache cannot answer, the resident shards do
        corpus_warm = [
            submit_corpus(_pattern_set(args.patterns, seed=100 + i))
            for i in range(reps)
        ]
        # model-warm ONLY: a known set, the corpus evicted per submit —
        # the data path (read/pack/upload) is paid again every time
        model_warm = []
        for _ in range(reps):
            corpus_cache_clear()
            model_warm.append(submit_corpus(set_a))
        # both warm: the last model-warm submit left the shards resident
        both = [submit_corpus(set_a) for _ in range(reps)]

        status = call("GET", "/status")
        service.stop()
        server.shutdown()

        both_s = min(both)
        rec = {
            "bench": "service_warm",
            "mode": "corpus_warm",
            "patterns": args.patterns,
            "files": args.files,
            "bytes": total,
            "backend": jax.default_backend(),
            "cold_s": round(cold_s, 4),
            "corpus_warm_s": round(min(corpus_warm), 4),
            "model_warm_s": round(min(model_warm), 4),
            "both_warm_s": round(both_s, 4),
            "speedup_corpus_only": (
                round(cold_s / min(corpus_warm), 3) if min(corpus_warm) else 0.0
            ),
            "speedup_model_only": (
                round(cold_s / min(model_warm), 3) if min(model_warm) else 0.0
            ),
            "speedup_both": round(cold_s / both_s, 3) if both_s else 0.0,
            "compile_cache_hits": int(
                status["compile_cache"].get("compile_cache_hits", 0)
            ),
            "corpus_cache_hits": int(
                status["corpus_cache"].get("corpus_cache_hits", 0)
            ),
            "bytes_resident": int(
                status["corpus_cache"].get("corpus_cache_bytes_resident", 0)
            ),
        }
        print(json.dumps(rec))  # exactly one JSON line
        if args.check and not both_s < cold_s:
            return 1
        return 0

    # cold: first time each set is seen (engine constructed, cache miss)
    cold_a = submit_and_wait(set_a)
    cold_b = submit_and_wait(set_b)
    # warm: alternate A/B so every submit reconfigures through the cache
    warm = []
    for _ in range(args.warm_reps):
        warm.append(submit_and_wait(set_a))
        warm.append(submit_and_wait(set_b))
    cache = call("GET", "/status")["compile_cache"]
    service.stop()
    server.shutdown()

    cold_s = min(cold_a, cold_b)
    warm_s = min(warm)
    rec = {
        "bench": "service_warm",
        "patterns": args.patterns,
        "cold_s": round(cold_s, 4),
        "warm_s": round(warm_s, 4),
        "speedup": round(cold_s / warm_s, 3) if warm_s else 0.0,
        "compile_cache_hits": int(cache.get("compile_cache_hits", 0)),
        "compile_cache_misses": int(cache.get("compile_cache_misses", 0)),
    }
    print(json.dumps(rec))  # exactly one JSON line (driver contract shape)
    if args.check and not warm_s < cold_s:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Cold-vs-warm submit latency against the grep-as-a-service daemon.

ISSUE 6's acceptance bar: a repeated pattern's second submit to a running
daemon must be strictly faster than the first on this CPU box, because the
cross-job compiled-model cache (ops/engine.cached_engine) skips engine
construction — off-chip, AC-bank/model compile for a large literal set
dominates a small job's wall, so the effect is CPU-measurable (on a real
chip the same cache additionally skips the ~20-40 s first XLA/Mosaic
compile per fresh shape key).

    python benchmarks/service_warm.py [--patterns 1500] [--warm-reps 3]
        [--check]

Drives the REAL surface end to end: ServiceServer HTTP API (POST /jobs,
GET /jobs/<id>), one in-process worker (deterministic warm path: the one
worker's second configure must come from the cache, not a sibling's).
Submits alternate between two equal-sized pattern sets A/B so every warm
submit pays a real reconfigure THROUGH the cache (the app-level same-config
short-circuit cannot answer it).  Prints exactly ONE JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import string
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

# Runnable as `python benchmarks/...` from anywhere: the repo root joins
# the FRONT of sys.path so the checkout being benchmarked always wins.
_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

# CPU-pinned (CLAUDE.md environment rules): ASSIGN, never setdefault — a
# tunneled-TPU default backend would price the submit path with device
# dispatch latency, and this benchmark measures host-side model build —
# AND pop the axon plugin factory: backend discovery calls every
# registered factory even under jax_platforms=cpu, and a black-holed
# tunnel blocks that call forever (same as tests/conftest.py).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("DGREP_NO_CALIBRATE", "1")
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")


def _pattern_set(n: int, seed: int) -> list[str]:
    rng = random.Random(seed)
    out = set()
    while len(out) < n:
        out.add("".join(
            rng.choice(string.ascii_lowercase)
            for _ in range(rng.randint(5, 12))
        ))
    return sorted(out)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--patterns", type=int, default=1500,
                    help="literal-set size per job (model build dominates)")
    ap.add_argument("--warm-reps", type=int, default=3,
                    help="warm submits per set; the MIN is reported")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless warm < cold")
    args = ap.parse_args()

    from distributed_grep_tpu.runtime.service import GrepService, ServiceServer
    from distributed_grep_tpu.utils.config import JobConfig

    root = Path(tempfile.mkdtemp(prefix="dgrep-svc-warm-"))
    corpus = root / "corpus.txt"
    corpus.write_bytes(b"".join(
        f"line {i} with some words in it\n".encode() for i in range(2000)
    ))

    service = GrepService(work_root=root / "svc")
    server = ServiceServer(service)
    server.start()
    service.start_local_workers(1)
    base = f"http://127.0.0.1:{server.port}"

    def call(method: str, path: str, body: bytes | None = None) -> dict:
        req = urllib.request.Request(f"{base}{path}", data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    def submit_and_wait(patterns: list[str]) -> float:
        cfg = JobConfig(
            input_files=[str(corpus)],
            application="distributed_grep_tpu.apps.grep_tpu",
            app_options={"patterns": patterns, "backend": "cpu"},
            n_reduce=2,
            journal=False,
        )
        t0 = time.perf_counter()
        job_id = call("POST", "/jobs", cfg.to_json().encode("utf-8"))["job_id"]
        while True:
            st = call("GET", f"/jobs/{job_id}")
            if st["state"] in ("done", "failed", "cancelled"):
                break
            time.sleep(0.01)
        dt = time.perf_counter() - t0
        if st["state"] != "done":
            raise RuntimeError(f"job {job_id} ended {st['state']}: {st}")
        return dt

    set_a = _pattern_set(args.patterns, seed=1)
    set_b = _pattern_set(args.patterns, seed=2)

    # cold: first time each set is seen (engine constructed, cache miss)
    cold_a = submit_and_wait(set_a)
    cold_b = submit_and_wait(set_b)
    # warm: alternate A/B so every submit reconfigures through the cache
    warm = []
    for _ in range(args.warm_reps):
        warm.append(submit_and_wait(set_a))
        warm.append(submit_and_wait(set_b))
    cache = call("GET", "/status")["compile_cache"]
    service.stop()
    server.shutdown()

    cold_s = min(cold_a, cold_b)
    warm_s = min(warm)
    rec = {
        "bench": "service_warm",
        "patterns": args.patterns,
        "cold_s": round(cold_s, 4),
        "warm_s": round(warm_s, 4),
        "speedup": round(cold_s / warm_s, 3) if warm_s else 0.0,
        "compile_cache_hits": int(cache.get("compile_cache_hits", 0)),
        "compile_cache_misses": int(cache.get("compile_cache_misses", 0)),
    }
    print(json.dumps(rec))  # exactly one JSON line (driver contract shape)
    if args.check and not warm_s < cold_s:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

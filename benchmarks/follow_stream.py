"""Standing-query streaming receipt (round 17, runtime/follow.py).

An appender thread grows a log file while a follow job stands over it on
a real daemon (ServiceServer HTTP API: POST /jobs with follow=true, then
GET /jobs/<id>/stream driven with a moving cursor).  Reports exactly ONE
JSON line: matched lines/s through the stream, and append-to-emit
latency p50/p95 (per appended batch: the wall from the append's flush to
the stream reply that carried its lines — poll cadence + suffix scan +
long-poll delivery, the whole wake path).

    python benchmarks/follow_stream.py [--lines 4000] [--batch 50]
        [--append-hz 40] [--poll-s 0.05] [--check]

``--check`` additionally pins the exactness contract: the streamed
(line, text) set must equal a one-shot engine scan over the FINAL file
bytes (the oracle every follow test pins — append boundaries, the
mid-line split carry, and the unterminated tail must all be invisible).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request
from pathlib import Path

# Runnable as `python benchmarks/...` from anywhere: the repo root joins
# the FRONT of sys.path so the checkout being benchmarked always wins.
_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

# CPU-pinned (CLAUDE.md environment rules): ASSIGN, never setdefault, and
# pop the axon plugin factory — backend discovery calls every registered
# factory even under jax_platforms=cpu, and a black-holed tunnel blocks
# that call forever (same as tests/conftest.py).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("DGREP_NO_CALIBRATE", "1")
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")


def _pct(xs: list[float], q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[i]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lines", type=int, default=4000,
                    help="matched lines to append in total")
    ap.add_argument("--batch", type=int, default=50,
                    help="lines per append flush (one latency sample each)")
    ap.add_argument("--append-hz", type=float, default=40.0,
                    help="append flushes per second (0 = as fast as possible)")
    ap.add_argument("--poll-s", type=float, default=0.05,
                    help="standing-query wake cadence (DGREP_FOLLOW_POLL_S)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless the streamed set equals the "
                         "one-shot oracle over the final file bytes")
    args = ap.parse_args()

    os.environ["DGREP_FOLLOW_POLL_S"] = str(args.poll_s)

    from distributed_grep_tpu.runtime.service import GrepService, ServiceServer
    from distributed_grep_tpu.utils.config import JobConfig

    root = Path(tempfile.mkdtemp(prefix="dgrep-follow-"))
    log_path = root / "app.log"
    log_path.write_bytes(b"")

    service = GrepService(work_root=root / "svc")
    server = ServiceServer(service)
    server.start()
    base = f"http://127.0.0.1:{server.port}"

    def call(method: str, path: str, body: bytes | None = None,
             timeout: float = 30.0) -> dict:
        req = urllib.request.Request(f"{base}{path}", data=body,
                                     method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())

    cfg = JobConfig(
        input_files=[str(log_path)],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": "hello", "backend": "cpu"},
        work_dir="ignored",
        follow=True,
    )
    jid = call("POST", "/jobs", cfg.to_json().encode("utf-8"))["job_id"]

    n_batches = max(1, args.lines // args.batch)
    # per appended line: perf_counter at the flush that made it visible
    flush_t: dict[int, float] = {}
    period = 1.0 / args.append_hz if args.append_hz > 0 else 0.0

    def appender() -> None:
        ln = 0
        with open(log_path, "ab") as f:
            for _b in range(n_batches):
                chunk = b"".join(
                    b"hello line %d payload xyz\n" % (ln + i)
                    for i in range(args.batch)
                )
                # mid-line split carry exercised every other batch: the
                # next flush completes the torn line (the streamed set
                # must still equal the oracle — --check pins it)
                if _b % 2 == 0:
                    f.write(chunk[:-9])
                    f.flush()
                    f.write(chunk[-9:])
                else:
                    f.write(chunk)
                f.flush()
                t = time.perf_counter()
                for i in range(args.batch):
                    flush_t[ln + i] = t
                ln += args.batch
                if period:
                    time.sleep(period)

    t_app = threading.Thread(target=appender)
    t0 = time.perf_counter()
    t_app.start()

    streamed: dict[int, str] = {}  # 0-based appended index -> text
    latency: list[float] = []
    cursor = 0
    dropped = 0
    deadline = time.monotonic() + 120.0
    while len(streamed) < n_batches * args.batch:
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"stream stuck at {len(streamed)}/{n_batches * args.batch}"
            )
        r = call("GET", f"/jobs/{jid}/stream?cursor={cursor}&timeout=5")
        now = time.perf_counter()
        cursor = int(r.get("next", cursor))
        dropped += int(r.get("dropped", 0))
        for rec in r.get("records") or []:
            idx = rec["line"] - 1
            streamed[idx] = rec["text"]
            if idx in flush_t:
                latency.append(now - flush_t[idx])
    wall = time.perf_counter() - t0
    t_app.join()

    final = log_path.read_bytes()
    status = call("GET", "/status")
    call("POST", f"/jobs/{jid}/cancel", b"")
    service.stop()
    server.shutdown()

    ok = True
    if args.check:
        # oracle: a one-shot engine scan of the final file state — the
        # streamed emissions across every wake must equal it exactly
        from distributed_grep_tpu.ops import lines as lines_mod
        from distributed_grep_tpu.ops.engine import GrepEngine

        eng = GrepEngine("hello", backend="cpu")
        res = eng.scan(final)
        nl = lines_mod.newline_index(final)
        want = {}
        for ln in res.matched_lines.tolist():
            s, e = lines_mod.line_span(nl, int(ln), len(final))
            # span end excludes the newline
            want[int(ln) - 1] = final[s:e].decode("utf-8", "surrogateescape")
        ok = streamed == want and dropped == 0

    fol = status.get("follow", {})
    rec = {
        "bench": "follow_stream",
        "lines": n_batches * args.batch,
        "batch": args.batch,
        "poll_s": args.poll_s,
        "wall_s": round(wall, 4),
        "lines_per_s": round(len(streamed) / wall, 1) if wall else 0.0,
        "latency_p50_ms": round(_pct(latency, 0.50) * 1e3, 2),
        "latency_p95_ms": round(_pct(latency, 0.95) * 1e3, 2),
        "follow_wakes": int(fol.get("follow_wakes", 0)),
        "suffix_bytes_scanned": int(fol.get("suffix_bytes_scanned", 0)),
        "dropped": dropped,
        **({"check": "ok" if ok else "FAIL"} if args.check else {}),
    }
    print(json.dumps(rec))  # exactly one JSON line
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Standing-query streaming receipt (round 17, runtime/follow.py).

An appender thread grows a log file while a follow job stands over it on
a real daemon (ServiceServer HTTP API: POST /jobs with follow=true, then
GET /jobs/<id>/stream driven with a moving cursor).  Reports exactly ONE
JSON line: matched lines/s through the stream, and append-to-emit
latency p50/p95 (per appended batch: the wall from the append's flush to
the stream reply that carried its lines — poll cadence + suffix scan +
long-poll delivery, the whole wake path).

    python benchmarks/follow_stream.py [--lines 4000] [--batch 50]
        [--append-hz 40] [--poll-s 0.05] [--check]

``--check`` additionally pins the exactness contract: the streamed
(line, text) set must equal a one-shot engine scan over the FINAL file
bytes (the oracle every follow test pins — append boundaries, the
mid-line split carry, and the unterminated tail must all be invisible).

Fused-tier receipt (round 21): ``--tenants K`` stands K queries (one
follow job each, distinct per-tenant marker patterns) over ONE appended
log and A/B-interleaves the fused daemon (DGREP_FOLLOW_FUSE on — all K
ride one group wake: one stat + one union suffix scan per (file, wake))
against DGREP_FOLLOW_FUSE=0 (K solo wake loops, each re-reading the same
appended bytes), ``--reps`` rounds each, reporting per-leg medians in
the one JSON line.  ``--check`` then gates (a) per-tenant exactness:
every tenant's streamed set equals its own one-shot oracle over the
final bytes, both legs, zero drops; (b) counter flatness: the fused
leg's suffix_bytes_scanned stays within 1.25x of the final file size
(K=1's floor — each appended byte consumed ONCE for the whole group)
while the unfused leg pays ~K x.  Aggregate lines/s and p95
append-to-emit ratios are REPORTED, not gated (this box's load swings
2x — compare medians across runs, CLAUDE.md round 12).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request
from pathlib import Path

# Runnable as `python benchmarks/...` from anywhere: the repo root joins
# the FRONT of sys.path so the checkout being benchmarked always wins.
_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

# CPU-pinned (CLAUDE.md environment rules): ASSIGN, never setdefault, and
# pop the axon plugin factory — backend discovery calls every registered
# factory even under jax_platforms=cpu, and a black-holed tunnel blocks
# that call forever (same as tests/conftest.py).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("DGREP_NO_CALIBRATE", "1")
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")


def _pct(xs: list[float], q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[i]


def _tenant_pattern(k: int) -> str:
    return f"t{k:02d}x"


def _tenant_line(ln: int, tenants: int) -> bytes:
    mark = _tenant_pattern(ln % tenants).encode()
    return b"hello line %d %s payload\n" % (ln, mark)


def _oracle(pattern: str, final: bytes) -> dict[int, str]:
    """0-based line index -> text for a one-shot scan of the final bytes."""
    from distributed_grep_tpu.ops import lines as lines_mod
    from distributed_grep_tpu.ops.engine import GrepEngine

    eng = GrepEngine(pattern, backend="cpu")
    res = eng.scan(final)
    nl = lines_mod.newline_index(final)
    want = {}
    for ln in res.matched_lines.tolist():
        s, e = lines_mod.line_span(nl, int(ln), len(final))
        want[int(ln) - 1] = final[s:e].decode("utf-8", "surrogateescape")
    return want


def _run_multi_leg(args, fuse_on: bool):
    """One daemon lifecycle: K follow tenants over one appended log.
    Returns (wall_s, latency samples, per-tenant streamed dicts, final
    bytes, /status follow view, dropped)."""
    import importlib

    from distributed_grep_tpu.runtime import follow as follow_mod
    from distributed_grep_tpu.runtime.service import GrepService, ServiceServer
    from distributed_grep_tpu.utils.config import JobConfig

    importlib.invalidate_caches()
    os.environ["DGREP_FOLLOW_FUSE"] = "1" if fuse_on else "0"
    # one group must host every tenant (the registry cap defaults to 8)
    os.environ["DGREP_FUSE_MAX_QUERIES"] = str(max(2, args.tenants))
    # a follow job holds a running slot for its lifetime — K standing
    # tenants need K concurrent admissions (the daemon default is 4)
    os.environ["DGREP_SERVICE_MAX_JOBS"] = str(max(4, args.tenants))
    follow_mod.follow_counters_clear()
    follow_mod.follow_fused_counters_clear()

    root = Path(tempfile.mkdtemp(prefix="dgrep-follow-ab-"))
    log_path = root / "app.log"
    log_path.write_bytes(b"")

    service = GrepService(work_root=root / "svc")
    server = ServiceServer(service)
    server.start()
    base = f"http://127.0.0.1:{server.port}"

    def call(method: str, path: str, body: bytes | None = None,
             timeout: float = 30.0) -> dict:
        req = urllib.request.Request(f"{base}{path}", data=body,
                                     method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())

    jids = []
    for k in range(args.tenants):
        cfg = JobConfig(
            input_files=[str(log_path)],
            application="distributed_grep_tpu.apps.grep_tpu",
            app_options={"pattern": _tenant_pattern(k), "backend": "cpu"},
            work_dir="ignored",
            follow=True,
            follow_poll_s=args.poll_s,
        )
        jids.append(call("POST", "/jobs",
                         cfg.to_json().encode("utf-8"))["job_id"])

    total = max(args.tenants, args.lines)
    n_batches = max(1, total // args.batch)
    total = n_batches * args.batch
    flush_t: dict[int, float] = {}
    period = 1.0 / args.append_hz if args.append_hz > 0 else 0.0

    def appender() -> None:
        ln = 0
        with open(log_path, "ab") as f:
            for _b in range(n_batches):
                chunk = b"".join(
                    _tenant_line(ln + i, args.tenants)
                    for i in range(args.batch)
                )
                if _b % 2 == 0:  # mid-line split carry, as in the K=1 leg
                    f.write(chunk[:-9])
                    f.flush()
                    f.write(chunk[-9:])
                else:
                    f.write(chunk)
                f.flush()
                t = time.perf_counter()
                for i in range(args.batch):
                    flush_t[ln + i] = t
                ln += args.batch
                if period:
                    time.sleep(period)

    expected = [len([1 for ln in range(total) if ln % args.tenants == k])
                for k in range(args.tenants)]
    streamed: list[dict[int, str]] = [{} for _ in range(args.tenants)]
    latency: list[float] = []
    dropped = [0] * args.tenants
    lat_lock = threading.Lock()
    done_t = [0.0] * args.tenants

    def drain(k: int) -> None:
        cursor = 0
        deadline = time.monotonic() + 180.0
        while len(streamed[k]) < expected[k]:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"tenant {k} stuck at {len(streamed[k])}/{expected[k]}")
            r = call("GET",
                     f"/jobs/{jids[k]}/stream?cursor={cursor}&timeout=5")
            now = time.perf_counter()
            cursor = int(r.get("next", cursor))
            dropped[k] += int(r.get("dropped", 0))
            for rec in r.get("records") or []:
                idx = rec["line"] - 1
                streamed[k][idx] = rec["text"]
                if idx in flush_t:
                    with lat_lock:
                        latency.append(now - flush_t[idx])
        done_t[k] = time.perf_counter()

    drains = [threading.Thread(target=drain, args=(k,))
              for k in range(args.tenants)]
    t_app = threading.Thread(target=appender)
    t0 = time.perf_counter()
    t_app.start()
    for t in drains:
        t.start()
    for t in drains:
        t.join()
    wall = max(done_t) - t0
    t_app.join()

    final = log_path.read_bytes()
    status = call("GET", "/status")
    for jid in jids:
        call("POST", f"/jobs/{jid}/cancel", b"")
    service.stop()
    server.shutdown()
    return wall, latency, streamed, final, status.get("follow", {}), sum(dropped)


def _median(xs: list[float]) -> float:
    xs = sorted(xs)
    return xs[len(xs) // 2] if xs else 0.0


def run_multi(args) -> int:
    """Interleaved A/B: fused daemon vs DGREP_FOLLOW_FUSE=0, K tenants."""
    legs = {"fused": [], "unfused": []}
    checks_ok = True
    flat_ok = True
    for _rep in range(args.reps):
        for name, fuse_on in (("fused", True), ("unfused", False)):
            wall, lat, streamed, final, fol, dropped = _run_multi_leg(
                args, fuse_on)
            n = sum(len(s) for s in streamed)
            legs[name].append({
                "wall": wall,
                "lines_per_s": n / wall if wall else 0.0,
                "p50": _pct(lat, 0.50), "p95": _pct(lat, 0.95),
                "wakes": int(fol.get("follow_wakes", 0)),
                "suffix_bytes": int(fol.get("suffix_bytes_scanned", 0)),
                "fused_wakes": int(fol.get("follow_fused_wakes", 0)),
                "bytes_saved": int(fol.get("follow_suffix_bytes_saved", 0)),
                "final_bytes": len(final),
            })
            if args.check:
                for k in range(args.tenants):
                    want = _oracle(_tenant_pattern(k), final)
                    if streamed[k] != want:
                        checks_ok = False
                if dropped:
                    checks_ok = False
                if fuse_on:
                    # flatness: the group consumed each appended byte ONCE
                    # regardless of K (K=1's floor is the file size)
                    suffix = int(fol.get("suffix_bytes_scanned", 0))
                    if suffix > 1.25 * len(final):
                        flat_ok = False

    fused = legs["fused"]
    unfused = legs["unfused"]
    lps_f = _median([leg["lines_per_s"] for leg in fused])
    lps_u = _median([leg["lines_per_s"] for leg in unfused])
    p95_f = _median([leg["p95"] for leg in fused])
    p95_u = _median([leg["p95"] for leg in unfused])
    ok = checks_ok and flat_ok
    rec = {
        "bench": "follow_stream_fused",
        "tenants": args.tenants,
        "lines": max(args.tenants, args.lines),
        "poll_s": args.poll_s,
        "reps": args.reps,
        "fused": {
            "lines_per_s": round(lps_f, 1),
            "latency_p50_ms": round(_median([leg["p50"] for leg in fused]) * 1e3, 2),
            "latency_p95_ms": round(p95_f * 1e3, 2),
            "follow_wakes": fused[-1]["wakes"],
            "suffix_bytes_scanned": fused[-1]["suffix_bytes"],
            "follow_fused_wakes": fused[-1]["fused_wakes"],
            "follow_suffix_bytes_saved": fused[-1]["bytes_saved"],
        },
        "unfused": {
            "lines_per_s": round(lps_u, 1),
            "latency_p50_ms": round(_median([leg["p50"] for leg in unfused]) * 1e3, 2),
            "latency_p95_ms": round(p95_u * 1e3, 2),
            "follow_wakes": unfused[-1]["wakes"],
            "suffix_bytes_scanned": unfused[-1]["suffix_bytes"],
        },
        "final_bytes": fused[-1]["final_bytes"],
        "suffix_bytes_ratio": round(
            unfused[-1]["suffix_bytes"] / max(1, fused[-1]["suffix_bytes"]), 2),
        "lines_per_s_ratio": round(lps_f / lps_u, 2) if lps_u else 0.0,
        "p95_ratio": round(p95_f / p95_u, 2) if p95_u else 0.0,
        **({"check": "ok" if ok else "FAIL"} if args.check else {}),
    }
    print(json.dumps(rec))  # exactly one JSON line
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lines", type=int, default=4000,
                    help="matched lines to append in total")
    ap.add_argument("--batch", type=int, default=50,
                    help="lines per append flush (one latency sample each)")
    ap.add_argument("--append-hz", type=float, default=40.0,
                    help="append flushes per second (0 = as fast as possible)")
    ap.add_argument("--poll-s", type=float, default=0.05,
                    help="standing-query wake cadence (DGREP_FOLLOW_POLL_S)")
    ap.add_argument("--tenants", type=int, default=1,
                    help=">1 = fused-tier A/B: K standing queries over one "
                         "appender, fused vs DGREP_FOLLOW_FUSE=0")
    ap.add_argument("--reps", type=int, default=2,
                    help="A/B rounds per leg in --tenants mode (medians)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless the streamed set equals the "
                         "one-shot oracle over the final file bytes")
    args = ap.parse_args()

    os.environ["DGREP_FOLLOW_POLL_S"] = str(args.poll_s)
    if args.tenants > 1:
        return run_multi(args)

    from distributed_grep_tpu.runtime.service import GrepService, ServiceServer
    from distributed_grep_tpu.utils.config import JobConfig

    root = Path(tempfile.mkdtemp(prefix="dgrep-follow-"))
    log_path = root / "app.log"
    log_path.write_bytes(b"")

    service = GrepService(work_root=root / "svc")
    server = ServiceServer(service)
    server.start()
    base = f"http://127.0.0.1:{server.port}"

    def call(method: str, path: str, body: bytes | None = None,
             timeout: float = 30.0) -> dict:
        req = urllib.request.Request(f"{base}{path}", data=body,
                                     method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())

    cfg = JobConfig(
        input_files=[str(log_path)],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": "hello", "backend": "cpu"},
        work_dir="ignored",
        follow=True,
    )
    jid = call("POST", "/jobs", cfg.to_json().encode("utf-8"))["job_id"]

    n_batches = max(1, args.lines // args.batch)
    # per appended line: perf_counter at the flush that made it visible
    flush_t: dict[int, float] = {}
    period = 1.0 / args.append_hz if args.append_hz > 0 else 0.0

    def appender() -> None:
        ln = 0
        with open(log_path, "ab") as f:
            for _b in range(n_batches):
                chunk = b"".join(
                    b"hello line %d payload xyz\n" % (ln + i)
                    for i in range(args.batch)
                )
                # mid-line split carry exercised every other batch: the
                # next flush completes the torn line (the streamed set
                # must still equal the oracle — --check pins it)
                if _b % 2 == 0:
                    f.write(chunk[:-9])
                    f.flush()
                    f.write(chunk[-9:])
                else:
                    f.write(chunk)
                f.flush()
                t = time.perf_counter()
                for i in range(args.batch):
                    flush_t[ln + i] = t
                ln += args.batch
                if period:
                    time.sleep(period)

    t_app = threading.Thread(target=appender)
    t0 = time.perf_counter()
    t_app.start()

    streamed: dict[int, str] = {}  # 0-based appended index -> text
    latency: list[float] = []
    cursor = 0
    dropped = 0
    deadline = time.monotonic() + 120.0
    while len(streamed) < n_batches * args.batch:
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"stream stuck at {len(streamed)}/{n_batches * args.batch}"
            )
        r = call("GET", f"/jobs/{jid}/stream?cursor={cursor}&timeout=5")
        now = time.perf_counter()
        cursor = int(r.get("next", cursor))
        dropped += int(r.get("dropped", 0))
        for rec in r.get("records") or []:
            idx = rec["line"] - 1
            streamed[idx] = rec["text"]
            if idx in flush_t:
                latency.append(now - flush_t[idx])
    wall = time.perf_counter() - t0
    t_app.join()

    final = log_path.read_bytes()
    status = call("GET", "/status")
    call("POST", f"/jobs/{jid}/cancel", b"")
    service.stop()
    server.shutdown()

    ok = True
    if args.check:
        # oracle: a one-shot engine scan of the final file state — the
        # streamed emissions across every wake must equal it exactly
        from distributed_grep_tpu.ops import lines as lines_mod
        from distributed_grep_tpu.ops.engine import GrepEngine

        eng = GrepEngine("hello", backend="cpu")
        res = eng.scan(final)
        nl = lines_mod.newline_index(final)
        want = {}
        for ln in res.matched_lines.tolist():
            s, e = lines_mod.line_span(nl, int(ln), len(final))
            # span end excludes the newline
            want[int(ln) - 1] = final[s:e].decode("utf-8", "surrogateescape")
        ok = streamed == want and dropped == 0

    fol = status.get("follow", {})
    rec = {
        "bench": "follow_stream",
        "lines": n_batches * args.batch,
        "batch": args.batch,
        "poll_s": args.poll_s,
        "wall_s": round(wall, 4),
        "lines_per_s": round(len(streamed) / wall, 1) if wall else 0.0,
        "latency_p50_ms": round(_pct(latency, 0.50) * 1e3, 2),
        "latency_p95_ms": round(_pct(latency, 0.95) * 1e3, 2),
        "follow_wakes": int(fol.get("follow_wakes", 0)),
        "suffix_bytes_scanned": int(fol.get("suffix_bytes_scanned", 0)),
        "dropped": dropped,
        **({"check": "ok" if ok else "FAIL"} if args.check else {}),
    }
    print(json.dumps(rec))  # exactly one JSON line
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

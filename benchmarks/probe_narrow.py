"""Probe: do 16-bit (and signed 8-bit) VECTOR ops compile in Mosaic on this
chip, and how fast is a 16-bit shift-and step vs the production 32-bit one?

Motivation: the shift-and kernel (ops/pallas_scan.py) is ALU-bound at ~240
GB/s with every per-byte op running on i32-widened (32,128) tiles = 4 vregs
per array op.  Short patterns (<= 15 positions + match bit) fit their state
and B-masks in 16 bits; if Mosaic compiles i16 compares/selects/shifts, the
whole per-byte loop halves its vreg traffic -> ~2x ceiling.  u8 compares are
KNOWN to crash Mosaic (CLAUDE.md, probed 2026-07-30); i16 is unprobed.

    PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/probe_narrow.py compile16
    ... probe_narrow.py slope      # i32 vs i16 kernel GB/s, 64 MB
    ... probe_narrow.py compile8   # signed-i8 compare (expected to crash)

Each probe prints one JSON line; run under a subprocess guard — a Mosaic
internal error can abort the process.
"""

from __future__ import annotations

import functools
import json
import sys
from pathlib import Path

_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

import numpy as np

SUBLANES = 32
LANE_COLS = 128
CHUNK_BLOCK_WORDS = 16


def _mini_kernel(data_ref, out_ref, state_ref, *, dt_name: str, steps: int):
    """Shift-and-shaped loop at a chosen element width.

    3 compare classes (the config-1 rare-class filter shape), coarse word
    accumulation, state carried in scratch at the narrow width."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    dt = dict(i32=jnp.int32, i16=jnp.int16, i8=jnp.int8)[dt_name]
    ut = dict(i32=jnp.uint32, i16=jnp.uint16, i8=jnp.uint8)[dt_name]
    ci = pl.program_id(1)

    @pl.when(ci == 0)
    def _init():
        state_ref[:] = jnp.zeros_like(state_ref)

    classes = ((ord("v"), 0b0000001), (ord("o"), 0b1000010),
               (ord("l"), 0b0000100), (ord("c"), 0b0001000),
               (ord("a"), 0b0010000), (ord("n"), 0b0100000))
    match_bit = 1 << 6
    wildcard = 0

    def word_body(w, s):
        word = jnp.zeros((SUBLANES, LANE_COLS), dtype=ut)
        for tt in range(32):
            b = data_ref[w * 32 + tt].astype(dt)
            bmask = jnp.full((SUBLANES, LANE_COLS), ut(wildcard))
            for val, mask in classes:
                hit = b == val
                bmask = bmask | jnp.where(hit, ut(mask), ut(0))
            s = ((s << ut(1)) | ut(1)) & bmask
            word = word | s
        out_ref[w] = (word & ut(match_bit)).astype(jnp.uint32)
        return s

    final = jax.lax.fori_loop(0, steps // 32, word_body, state_ref[:])
    state_ref[:] = final


@functools.partial(
    __import__("jax").jit, static_argnames=("dt_name", "chunk", "lane_blocks")
)
def _run(data, *, dt_name, chunk, lane_blocks):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ut = dict(i32=jnp.uint32, i16=jnp.uint16, i8=jnp.uint8)[dt_name]
    steps = 32 * CHUNK_BLOCK_WORDS
    kernel = functools.partial(_mini_kernel, dt_name=dt_name, steps=steps)
    return pl.pallas_call(
        kernel,
        grid=(lane_blocks, chunk // steps),
        in_specs=[pl.BlockSpec((steps, SUBLANES, LANE_COLS),
                               lambda li, ci: (ci, li, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((CHUNK_BLOCK_WORDS, SUBLANES, LANE_COLS),
                               lambda li, ci: (ci, li, 0),
                               memory_space=pltpu.VMEM),
        out_shape=__import__("jax").ShapeDtypeStruct(
            (chunk // 32, lane_blocks * SUBLANES, LANE_COLS), np.uint32),
        scratch_shapes=[pltpu.VMEM((SUBLANES, LANE_COLS), ut)],
    )(data)


def _corpus(n):
    rng = np.random.default_rng(0)
    data = rng.integers(32, 127, size=n, dtype=np.uint8)
    data[rng.integers(0, n, size=n // 80)] = 0x0A
    needle = np.frombuffer(b"volcano", np.uint8)
    for p in rng.integers(0, n - 16, size=1000):
        data[p : p + len(needle)] = needle
    return data.tobytes()


def _setup(data: bytes):
    import jax
    import jax.numpy as jnp

    from distributed_grep_tpu.ops import layout as layout_mod

    lay = layout_mod.choose_layout(len(data), target_lanes=8192, min_chunk=512,
                                   lane_multiple=4096, chunk_multiple=512)
    arr = layout_mod.to_device_array(data, lay)
    pad_rows = 512
    pad = np.full((pad_rows, arr.shape[1]), 0x0A, dtype=np.uint8)
    full = np.concatenate([arr, pad], axis=0)
    lane_blocks = lay.lanes // 4096
    dev = jax.device_put(jnp.asarray(np.ascontiguousarray(
        full.reshape(full.shape[0], lane_blocks * SUBLANES, LANE_COLS))))
    return dev, lay, lane_blocks, pad_rows


def probe_compile(dt_name: str) -> None:
    data = _corpus(1 << 20)
    dev, lay, lane_blocks, _ = _setup(data)
    win = dev[: lay.chunk]
    out = _run(win, dt_name=dt_name, chunk=lay.chunk, lane_blocks=lane_blocks)
    n = int(np.count_nonzero(np.asarray(out)))
    print(json.dumps({"probe": f"compile_{dt_name}", "ok": True,
                      "nonzero_words": n}))


def probe_slope() -> None:
    import jax.numpy as jnp

    from distributed_grep_tpu.utils.slope import slope_per_pass

    data = _corpus(64 << 20)
    dev, lay, lane_blocks, pad_rows = _setup(data)
    for dt_name in ("i32", "i16"):
        def scan(win, dt_name=dt_name):
            out = _run(win, dt_name=dt_name, chunk=lay.chunk,
                       lane_blocks=lane_blocks)
            return jnp.count_nonzero(out)

        per_pass, cnt = slope_per_pass(dev, lay.chunk, pad_rows, scan,
                                       r1=2, r2=10, measurements=3)
        gbs = len(data) / per_pass / 1e9
        print(json.dumps({"probe": f"slope_{dt_name}", "gbs": round(gbs, 1),
                          "per_pass_ms": round(per_pass * 1e3, 2),
                          "count": int(cnt)}))


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "compile16"
    if which == "compile16":
        probe_compile("i16")
    elif which == "compile8":
        probe_compile("i8")
    elif which == "slope":
        probe_slope()
    else:
        raise SystemExit(f"unknown probe {which}")

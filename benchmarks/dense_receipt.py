"""Match-dense 64 MB receipt: CLI wall + host-side stage attribution.

The one workload where the host record pipeline, not the kernel, is the
wall (BASELINE.md rounds 4-6): a dense English-like corpus where ~40% of
lines match, so the job's cost is everything BETWEEN kernel output and
mr-out — record build, partition split, shuffle encode/decode, reduce
format, display merge.  This is the one-command reproduction of the
round-6 profile and the before/after receipt for the native map-record
pipeline (round 8, ``dgrep_build_records``):

    python benchmarks/dense_receipt.py              # wall + stage profile
    python benchmarks/dense_receipt.py --check      # + native-vs-off byte identity
    python benchmarks/dense_receipt.py --ab         # + CLI wall with the
                                                    #   record build forced
                                                    #   off (DGREP_NATIVE_RECORDS=0)

Stage times are accumulated by wrapping the pipeline's own entry points
(``_records_for``, ``bucketize``, ``encode_records``/``decode_records``,
``format_lines_bytes``) around an in-process job — the same attribution
method as the round-6 manual profile, now reproducible in one command.
The CLI leg runs ``python -m distributed_grep_tpu grep`` as a real
subprocess with stdout to a file (interpreter startup included — that is
the number BASELINE quotes as "CLI wall").  Prints exactly ONE JSON line.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

# Runnable as `python benchmarks/...` from anywhere: the repo root joins
# the FRONT of sys.path so the checkout being benchmarked always wins.
_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

# CPU-pinned (CLAUDE.md environment rules): ASSIGN, never setdefault.
# This benchmark measures the HOST record pipeline — the cpu engine path
# never imports jax, so no plugin-factory pop is needed here.
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402


def make_corpus(path: Path, mb: int, seed: int = 6) -> None:
    """English-shaped dense corpus: ~36-byte lines of lowercase words,
    'the' planted so ~40% of lines match (the round-6 receipt shape:
    733k matched of 1.78M lines at 64 MB)."""
    n = mb << 20
    rng = np.random.default_rng(seed)
    data = rng.integers(97, 123, size=n, dtype=np.uint8)  # a-z
    data[rng.integers(0, n, size=n // 6)] = 0x20
    data[rng.integers(0, n, size=n // 36)] = 0x0A
    pos = rng.integers(0, n - 4, size=n // 90)
    for i, b in enumerate(b"the"):
        data[pos + i] = b
    data[-1] = 0x0A
    path.write_bytes(data.tobytes())


class StageClock:
    """Accumulate wall time per stage by wrapping pipeline entry points.
    Sums are plain float adds under the GIL — worker threads race only
    benignly (same method as the round-6 manual profile)."""

    def __init__(self):
        self.totals: dict[str, float] = {}

    def wrap(self, obj, name: str, stage: str):
        fn = getattr(obj, name)

        @functools.wraps(fn)
        def timed(*a, **k):
            t0 = time.perf_counter()
            try:
                return fn(*a, **k)
            finally:
                self.totals[stage] = (
                    self.totals.get(stage, 0.0) + time.perf_counter() - t0
                )

        setattr(obj, name, timed)
        return fn


def run_inprocess(corpus: Path, pattern: str, work: Path,
                  clock: StageClock | None = None) -> dict:
    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.utils.config import JobConfig

    cfg = JobConfig(
        application="distributed_grep_tpu.apps.grep_tpu",
        input_files=[str(corpus)],
        work_dir=str(work),
        n_reduce=10,
        journal=False,
        app_options={"pattern": pattern, "backend": "cpu"},
    )
    t0 = time.perf_counter()
    res = run_job(cfg, n_workers=2)
    job_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    display = b"".join(res.display_blocks_sorted())
    display_s = time.perf_counter() - t1
    outs = {p.name: p.read_bytes() for p in res.output_files}
    out = {
        "job_s": round(job_s, 3),
        "display_s": round(display_s, 3),
        "matched_lines": display.count(b"\n"),
    }
    if clock is not None:
        out["stages"] = {k: round(v, 3) for k, v in
                        sorted(clock.totals.items())}
    out["_outs"] = outs
    out["_display"] = display
    return out


def profiled_run(corpus: Path, pattern: str, work: Path) -> dict:
    clock = StageClock()
    from distributed_grep_tpu.ops import lines as ops_lines
    from distributed_grep_tpu.ops.engine import GrepEngine
    from distributed_grep_tpu.runtime import columnar, shuffle

    clock.wrap(GrepEngine, "scan", "scan")
    # Wrap at the DEFINITION sites: the app loader gives each job a fresh
    # grep_tpu module instance whose `from ... import` bindings resolve at
    # load time (inside run_job, i.e. after these wraps land) — wrapping
    # the already-imported app module would miss the worker's copy.
    clock.wrap(columnar, "make_batch_from_lines", "record_build")
    clock.wrap(ops_lines, "newline_index", "newline_index")
    clock.wrap(shuffle, "bucketize", "bucketize_split")
    clock.wrap(shuffle, "encode_records", "shuffle_encode")
    clock.wrap(shuffle, "decode_records", "shuffle_decode")
    clock.wrap(columnar.IdentityCollator, "add_many", "collate_add")
    clock.wrap(columnar.LineBatch, "format_lines_bytes", "reduce_format")
    try:
        return run_inprocess(corpus, pattern, work, clock)
    finally:
        # wrappers are process-local and this process exits after the
        # run; nothing to restore for correctness, but be tidy anyway
        pass


def cli_wall(corpus: Path, pattern: str, extra_env: dict | None = None) -> float:
    env = dict(os.environ, PYTHONPATH=str(_root), JAX_PLATFORMS="cpu",
               **(extra_env or {}))
    with tempfile.NamedTemporaryFile() as out:
        t0 = time.perf_counter()
        r = subprocess.run(
            [sys.executable, "-m", "distributed_grep_tpu", "grep",
             pattern, str(corpus), "--backend", "cpu"],
            stdout=out, stderr=subprocess.PIPE, env=env, timeout=600,
        )
        wall = time.perf_counter() - t0
    if r.returncode not in (0, 1):
        raise RuntimeError(f"CLI failed rc={r.returncode}: {r.stderr[-500:]}")
    return wall


def check_byte_identity(corpus: Path, pattern: str, tmp: Path) -> dict:
    """Native record/merge loops ON vs ALL OFF (numpy fallbacks + the
    per-record spill path via a tiny reduce cap): mr-out files and display
    bytes must be byte-identical — the test_native_merge.py contract, run
    at receipt scale."""
    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.utils import native
    from distributed_grep_tpu.utils.config import JobConfig

    def run(tag: str) -> tuple[dict, bytes]:
        cfg = JobConfig(
            application="distributed_grep_tpu.apps.grep_tpu",
            input_files=[str(corpus)],
            work_dir=str(tmp / f"check-{tag}"),
            n_reduce=4,
            journal=False,
            reduce_memory_bytes=8 << 20,  # force collator spill runs
            app_options={"pattern": pattern, "backend": "cpu"},
        )
        res = run_job(cfg, n_workers=2)
        outs = {p.name: p.read_bytes() for p in res.output_files}
        return outs, b"".join(res.display_blocks_sorted())

    outs_on, disp_on = run("native")
    saved = {}
    for name in ("gather_ranges_native", "format_batch", "merge_display",
                 "build_records", "line_spans_native", "unique_lines_native"):
        if hasattr(native, name):
            saved[name] = getattr(native, name)
            setattr(native, name, lambda *a, **k: None)
    try:
        outs_off, disp_off = run("python")
    finally:
        for name, fn in saved.items():
            setattr(native, name, fn)
    ok = outs_on == outs_off and disp_on == disp_off
    return {"identical": ok, "mr_out_files": len(outs_on),
            "display_bytes": len(disp_on)}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=64)
    ap.add_argument("--pattern", default="the")
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--ab", action="store_true",
                    help="also time the CLI with DGREP_NATIVE_RECORDS=0")
    ap.add_argument("--skip-cli", action="store_true")
    args = ap.parse_args()

    result: dict = {"benchmark": "dense_receipt", "mb": args.mb,
                    "pattern": args.pattern}
    with tempfile.TemporaryDirectory(prefix="dgrep-dense-") as td:
        tmp = Path(td)
        corpus = tmp / "corpus.txt"
        t0 = time.perf_counter()
        make_corpus(corpus, args.mb)
        result["gen_s"] = round(time.perf_counter() - t0, 3)

        if not args.skip_cli:
            result["cli_wall_s"] = round(
                cli_wall(corpus, args.pattern), 3)
            if args.ab:
                result["cli_wall_records_off_s"] = round(
                    cli_wall(corpus, args.pattern,
                             {"DGREP_NATIVE_RECORDS": "0"}), 3)

        prof = profiled_run(corpus, args.pattern, tmp / "job")
        prof.pop("_outs")
        prof.pop("_display")
        result.update(prof)

        from distributed_grep_tpu.utils import native as _native

        result["native_available"] = _native.native_available()
        result["native_records"] = bool(
            getattr(_native, "build_records", None)
            and _native.native_available()
            and _native.env_native_records()
        ) if hasattr(_native, "env_native_records") else False

        if args.check:
            result["check"] = check_byte_identity(
                corpus, args.pattern, tmp)

    print(json.dumps(result))
    if args.check and not result["check"]["identical"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

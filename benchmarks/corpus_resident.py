"""Cold-vs-warm repeat-query throughput against the grep service with the
device corpus cache (round 7, ops/layout.CorpusCache) in force.

ISSUE 7's acceptance bar: a repeat query over the SAME inputs must skip
the host read, the stripe pack, and the HBM upload — the data path that
dominates a dense job's wall (BASELINE round 6: the scan kernel is ~12%).
Three warm legs separate the two caches' contributions:

* cold         — first submit: model miss + corpus miss (full data path)
* model_warm   — same pattern, corpus cache CLEARED first: the compiled-
                 model cache answers, the data path is paid again
* warm         — same pattern, both caches answer: the repeat-query
                 steady state (zero re-read / re-pack / re-upload)

    python benchmarks/corpus_resident.py [--files 64] [--file-kb 256]
        [--pattern volcano] [--warm-reps 3] [--timing e2e|slope] [--check]

Drives the REAL surface end to end: ServiceServer HTTP API, one in-process
worker (deterministic warm path), multi-file map splits handed to the
engine as PATHS (apps/grep_tpu.map_batch_paths) so the warm window is
recognized before any member is read.  ``--timing slope`` additionally
slope-times the device-resident rescan of the packed corpus
(utils/slope.py — the honest per-chip warm ceiling through a slow
tunnel; on this CPU-only box it reports the cpu number, re-run in a live
tunnel window for the real-chip receipt).  Prints exactly ONE JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

# Runnable as `python benchmarks/...` from anywhere: the repo root joins
# the FRONT of sys.path so the checkout being benchmarked always wins.
_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

# CPU-pinned (CLAUDE.md environment rules): ASSIGN, never setdefault — and
# pop the axon plugin factory (backend discovery calls every registered
# factory even under jax_platforms=cpu; a black-holed tunnel blocks that
# call forever).  ``--device`` drops the pin for a live tunnel window.
if "--device" not in sys.argv:
    os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("DGREP_NO_CALIBRATE", "1")
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

if "--device" not in sys.argv:
    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

WORDS = (
    "the of and to in a is that for it as was with be by on not he this are "
    "at from or have an they which one you were all her she there would "
    "fff needle volcano anarchism philosophy wikipedia"
).split()


def write_corpus(root: Path, n_files: int, file_bytes: int,
                 needle: bytes, seed: int = 9) -> list[Path]:
    """English-like filler files on disk; ~1 in 8 carries the needle (the
    log/code-search shape: most files miss, some hit)."""
    rng = np.random.default_rng(seed)
    paths = []
    for i in range(n_files):
        lines, n = [], 0
        while n < file_bytes:
            k = int(rng.integers(3, 12))
            line = b" ".join(
                WORDS[int(rng.integers(0, len(WORDS)))].encode()
                for _ in range(k)
            )
            lines.append(line)
            n += len(line) + 1
        blob = b"\n".join(lines)[:file_bytes - 1] + b"\n"
        if i % 8 == 0:
            pos = int(rng.integers(0, max(1, len(blob) - len(needle) - 2)))
            blob = blob[:pos] + needle + blob[pos + len(needle):]
        p = root / f"f{i:05d}.txt"
        p.write_bytes(blob)
        paths.append(p)
    return paths


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--files", type=int, default=64)
    ap.add_argument("--file-kb", type=float, default=256)
    ap.add_argument("--pattern", default="volcano")
    ap.add_argument("--warm-reps", type=int, default=3,
                    help="warm submits; the MIN is reported")
    ap.add_argument("--batch-mb", type=float, default=32)
    ap.add_argument("--corpus-mb", type=float, default=1024,
                    help="DGREP_CORPUS_BYTES-equivalent budget (app option)")
    ap.add_argument("--timing", default="e2e", choices=["e2e", "slope"],
                    help="slope: additionally slope-time the device-"
                         "resident rescan of the packed corpus")
    ap.add_argument("--index", action="store_true",
                    help="add a shard-index leg (all three caches warm) "
                         "so model / corpus / index are attributable "
                         "separately; the base legs always run with "
                         "DGREP_INDEX=0 so their meaning is unchanged")
    ap.add_argument("--device", action="store_true",
                    help="do NOT pin JAX_PLATFORMS=cpu (live tunnel window)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless warm < cold and outputs identical")
    args = ap.parse_args()

    from distributed_grep_tpu.ops.layout import corpus_cache_clear
    from distributed_grep_tpu.runtime.service import GrepService, ServiceServer
    from distributed_grep_tpu.utils.config import JobConfig

    root = Path(tempfile.mkdtemp(prefix="dgrep-corpus-res-"))
    (root / "in").mkdir()
    file_bytes = int(args.file_kb * 1024)
    paths = write_corpus(root / "in", args.files, file_bytes,
                         args.pattern.encode())
    total = sum(p.stat().st_size for p in paths)

    service = GrepService(work_root=root / "svc")
    server = ServiceServer(service)
    server.start()
    service.start_local_workers(1)
    base = f"http://127.0.0.1:{server.port}"

    def call(method: str, path: str, body: bytes | None = None) -> dict:
        req = urllib.request.Request(f"{base}{path}", data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=600) as r:
            return json.loads(r.read())

    def submit_and_wait() -> tuple[float, dict]:
        cfg = JobConfig(
            input_files=[str(p) for p in paths],
            application="distributed_grep_tpu.apps.grep_tpu",
            app_options={
                "pattern": args.pattern,
                "backend": "device",
                "corpus_bytes": int(args.corpus_mb * (1 << 20)),
            },
            batch_bytes=int(args.batch_mb * (1 << 20)),
            n_reduce=2,
            journal=False,
        )
        t0 = time.perf_counter()
        job_id = call("POST", "/jobs", cfg.to_json().encode("utf-8"))["job_id"]
        while True:
            st = call("GET", f"/jobs/{job_id}")
            if st["state"] in ("done", "failed", "cancelled"):
                break
            time.sleep(0.01)
        dt = time.perf_counter() - t0
        if st["state"] != "done":
            raise RuntimeError(f"job {job_id} ended {st['state']}: {st}")
        return dt, call("GET", f"/jobs/{job_id}/result")

    # The base legs run with the shard index OFF: their round-7 meaning
    # (model vs corpus attribution) is unchanged by the index tier — the
    # --index leg below measures the third cache separately.
    os.environ["DGREP_INDEX"] = "0"
    cold_s, cold_res = submit_and_wait()
    # model-warm leg: the compiled-model cache answers, but the corpus
    # cache is emptied — the submit pays the full data path again
    corpus_cache_clear()
    model_warm_s, _ = submit_and_wait()
    # warm legs: both caches answer (the first repopulated the corpus)
    warm, warm_res = [], None
    for _ in range(max(1, args.warm_reps)):
        dt, warm_res = submit_and_wait()
        warm.append(dt)
    warm_s = min(warm)

    index_warm_s = None
    index_res = None
    if args.index:
        # shard-index leg: all THREE caches answer.  One untimed pass
        # builds + persists the summaries; the timed reps then route —
        # shards the query cannot match are pruned at the planner, so
        # warm cost falls from O(corpus) toward O(matching shards).
        os.environ.pop("DGREP_INDEX", None)
        submit_and_wait()  # summary-building pass
        idx = []
        for _ in range(max(1, args.warm_reps)):
            dt, index_res = submit_and_wait()
            idx.append(dt)
        index_warm_s = min(idx)
    os.environ.pop("DGREP_INDEX", None)
    status = call("GET", "/status")
    corpus = status.get("corpus_cache", {})

    out = {
        "bench": "corpus_resident",
        "files": args.files,
        "bytes": total,
        "pattern": args.pattern,
        "backend": jax.default_backend(),
        "cold_s": round(cold_s, 4),
        "model_warm_s": round(model_warm_s, 4),
        "warm_s": round(warm_s, 4),
        "cold_gbps": round(total / 1e9 / cold_s, 3),
        "warm_gbps": round(total / 1e9 / warm_s, 3),
        "speedup_vs_cold": round(cold_s / warm_s, 3) if warm_s else 0.0,
        "speedup_vs_model_warm": (
            round(model_warm_s / warm_s, 3) if warm_s else 0.0
        ),
        "corpus_cache_hits": int(corpus.get("corpus_cache_hits", 0)),
        "corpus_cache_misses": int(corpus.get("corpus_cache_misses", 0)),
        "bytes_resident": int(corpus.get("corpus_cache_bytes_resident", 0)),
    }
    if index_warm_s is not None:
        out["index_warm_s"] = round(index_warm_s, 4)
        out["index_speedup_vs_warm"] = (
            round(warm_s / index_warm_s, 3) if index_warm_s else 0.0
        )
        out["index"] = status.get("index", {})

    if args.check:
        def by_name(res: dict) -> dict:
            return {Path(p).name: Path(p).read_bytes()
                    for p in res.get("outputs", [])}

        identical = by_name(cold_res) == by_name(warm_res)
        if index_res is not None:
            identical = identical and by_name(index_res) == by_name(cold_res)
        out["check"] = "ok" if identical else "MISMATCH"

    service.stop()
    server.shutdown()

    if args.timing == "slope":
        # Device-resident warm-rescan ceiling: pack the whole corpus once
        # and slope-time chained kernel passes over the resident layout
        # (utils/slope.py via the baseline suite's per-mode setup) — what
        # a warm query costs once the upload is cached away.
        sys.path.insert(0, str(_root / "benchmarks"))
        from baseline_configs import slope_gbps

        from distributed_grep_tpu.ops.engine import GrepEngine
        from distributed_grep_tpu.ops.layout import BatchPacker

        eng = GrepEngine(args.pattern, backend="device")
        packer = BatchPacker(total + args.files + 1)
        for p in paths:
            packer.add(p.name, p.read_bytes())
        got = slope_gbps(eng, packer.pack().data)
        if got is None:
            out["slope_error"] = f"no device slope path for mode {eng.mode}"
        else:
            gbps, label = got
            out["resident_slope_gbps"] = round(gbps, 3)
            out["engine"] = label

    print(json.dumps(out), flush=True)  # exactly one JSON line
    ok = out.get("check", "ok") == "ok" and (
        not args.check or warm_s < cold_s
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

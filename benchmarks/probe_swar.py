"""Probe: does a SWAR-packed shift-and kernel (4 corpus bytes per i32 lane
element) beat the 231 GB/s unpacked coarse kernel on this chip?

Motivation (round-6 VERDICT top_next): the production shift-and kernel
(ops/pallas_scan.py) is pinned at the VPU ALU roofline — every per-byte
vector op runs on i32 tiles carrying ONE corpus byte per 4-byte lane
element, so 3/4 of each ALU slot moves widened zeros.  The SWAR variant
(ops/pallas_scan.swar_shift_and_scan_words) packs FOUR STRIPES per u32
lane element (byte-interleaved — the u8 corpus bitcast to u32 over the
lane axis), keeps each stripe's automaton in its own byte of the state
tile, and detects byte-class hits with the EXACT packed zero-byte test

    y  = x ^ (v * 0x01010101)
    t  = y | ((y | 0x80808080) - 0x01010101)   # bit7 clear iff byte == v
    nz = ~t & 0x80808080

(borrow-free, unlike classic Mycroft `(y-1) & ~y & 0x80`, whose
cross-byte borrows over-report) — pure i32 arithmetic, no narrow-dtype
compares, so it dodges every Mosaic crash recorded in CLAUDE.md.

Why the alternative "4 CONSECUTIVE bytes of one stripe per u32" packing
was rejected without a probe: the shift-and recurrence is serial in the
byte index, so consecutive-byte packing still needs one B-mask tile PER
BYTE — the per-class hit extraction costs as many vector ops as the
compares it replaces, and nothing shrinks.  Stripe-interleaved packing is
the classic SWAR form: 4 INDEPENDENT automata advance per op.

Op-count analysis (per 4 corpus bytes, C single-value classes):
  unpacked: 4 x [C x (cmp + select-or) + 3 shift-and + 1 accumulate]
            ~ 4 x (2C + 4) vector ops
  packed:   C x (xor + or + sub + or + not-and = 6)
            + C x (shift + sub + and + or = 4 mask build)
            + 3 shift-and + 1 accumulate
            ~ 10C + 4 vector ops
  ratio at C=3: 40 / 34 ~ 1.2x; at C=6: 64 / 64 ~ 1.0x — BUT the packed
  tile carries 4x the corpus bytes per op, so bytes/op is 4 x (34/40)
  ~ 3.4x at C=3.  Accounting honestly per BYTE: unpacked ~ 2C+4 = 10
  ops/byte at C=3, packed ~ (10C+4)/4 = 8.5 ops/byte — plus the packed
  path loads u32 directly (no u8 -> i32 widen) and writes 1/4 the output
  words.  Predicted ~1.2-1.5x at C=3, shrinking as C grows.  The probe
  exists because this arithmetic ignores Mosaic scheduling; only a slope
  number decides.

Eligibility (models/shift_and.swar_values): pattern length <= 8 (state +
match bit per byte), every checked class a set of exact byte VALUES
(equality only — ranges have no cheap packed form), <= 16 values total.
Wildcards (the rare-class filter) are free, as in the unpacked kernel.

    PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/probe_swar.py exact
    ... probe_swar.py slope          # packed vs unpacked GB/s, 64 MB
    ... probe_swar.py slope --unrolls 8,16,32
    ... probe_swar.py exact --interpret   # CI smoke (CPU, small corpus)

Each probe prints one JSON line per measurement; run under a subprocess
guard — a Mosaic internal error can abort the process.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

PATTERNS = [
    # (pattern, ignore_case, filtered) — 'volcano' is the headline config;
    # filtered=True probes the production rare-class-filter shape (3
    # checked classes), False the full 7-class model; 'function' pins the
    # length-8 / match-bit-0x80 edge; ignore_case doubles the values.
    ("volcano", False, True),
    ("volcano", False, False),
    ("volcano", True, True),
    ("function", False, False),
]


def _corpus(n: int) -> bytes:
    rng = np.random.default_rng(0)
    data = rng.integers(32, 127, size=n, dtype=np.uint8)
    data[rng.integers(0, n, size=n // 80)] = 0x0A
    for lit in (b"volcano", b"function"):
        needle = np.frombuffer(lit, np.uint8)
        for p in rng.integers(0, n - 16, size=1000):
            data[p : p + len(needle)] = needle
    return data.tobytes()


def _model(pattern: str, ignore_case: bool, filtered: bool):
    from distributed_grep_tpu.models.shift_and import (
        filtered_for_device,
        try_compile_shift_and,
    )

    m = try_compile_shift_and(pattern, ignore_case=ignore_case)
    assert m is not None
    if filtered:
        f = filtered_for_device(m)
        if f is not None:
            return f
    return m


def _layout(data: bytes, target_lanes: int = 16384):
    from distributed_grep_tpu.ops import layout as layout_mod
    from distributed_grep_tpu.ops import pallas_scan

    lay = layout_mod.choose_layout(
        len(data), target_lanes=max(target_lanes,
                                    pallas_scan.SWAR_LANES_PER_BLOCK),
        min_chunk=512, lane_multiple=pallas_scan.SWAR_LANES_PER_BLOCK,
        chunk_multiple=512,
    )
    return lay, layout_mod.to_device_array(data, lay)


def probe_exact(interpret: bool, mb: int) -> int:
    """Compile both kernels for real and compare stripe-level candidate
    flags bit-exactly across every pattern shape.  Returns #failures."""
    from distributed_grep_tpu.models.shift_and import swar_values
    from distributed_grep_tpu.ops import pallas_scan

    data = _corpus(mb << 20)
    lay, arr = _layout(data)
    failures = 0
    for pattern, ic, filtered in PATTERNS:
        m = _model(pattern, ic, filtered)
        assert swar_values(m) is not None, (pattern, ic, filtered)
        t0 = time.time()
        try:
            wp = np.asarray(pallas_scan.swar_shift_and_scan_words(
                arr, m, interpret=interpret or None
            ))
        except Exception as e:  # noqa: BLE001 — report, continue
            failures += 1
            print(json.dumps({
                "probe": "swar_exact", "pattern": pattern, "ic": ic,
                "filtered": filtered, "ok": False,
                "error": str(e).replace("\n", " ")[:200],
            }), flush=True)
            continue
        dt = time.time() - t0
        wu = np.asarray(pallas_scan.shift_and_scan_words(
            arr, m, interpret=interpret or None, coarse=True
        ))
        nw = lay.chunk // 32
        fu = wu.reshape(nw, lay.lanes) != 0
        wpf = wp.reshape(nw, lay.lanes // 4)
        fp = np.zeros_like(fu)
        for k in range(4):
            fp[:, k::4] = ((wpf >> np.uint32(8 * k)) & np.uint32(0xFF)) != 0
        ok = bool(np.array_equal(fu, fp))
        if not ok:
            failures += 1
        print(json.dumps({
            "probe": "swar_exact", "pattern": pattern, "ic": ic,
            "filtered": filtered, "ok": ok, "spans": int(fu.sum()),
            "compile_s": round(dt, 1),
        }), flush=True)
    return failures


def probe_slope(mb: int, unrolls: list[int]) -> None:
    """Slope-time packed vs unpacked on the same corpus (utils/slope.py —
    naive timing through the tunnel reports ~0, CLAUDE.md)."""
    import jax.numpy as jnp

    from distributed_grep_tpu.ops import pallas_scan
    from distributed_grep_tpu.utils.slope import slope_per_pass

    data = _corpus(mb << 20)
    lay, arr = _layout(data)
    import jax

    # 512 '\n' pad rows: each chained rep scans an i-dependent window, or
    # XLA hoists the loop-invariant scan and reps time like one
    # (utils/slope.py docstring — the repo's timing invariant).
    pad_rows = 512
    pad = np.full((pad_rows, lay.lanes), 0x0A, dtype=np.uint8)
    dev = jax.device_put(jnp.asarray(np.concatenate([np.asarray(arr), pad],
                                                    axis=0)))
    for pattern, ic, filtered in PATTERNS:
        m = _model(pattern, ic, filtered)
        for unroll in unrolls:
            def packed_scan(win, m=m, unroll=unroll):
                return jnp.count_nonzero(
                    pallas_scan.swar_shift_and_scan_words(
                        win, m, interpret=False, unroll=unroll
                    )
                )

            def unpacked_scan(win, m=m):
                return jnp.count_nonzero(pallas_scan.shift_and_scan_words(
                    win, m, interpret=False, coarse=True
                ))

            for name, fn in (("swar", packed_scan),
                             ("unpacked", unpacked_scan)):
                if name == "unpacked" and unroll != unrolls[0]:
                    continue  # the baseline's unroll is fixed at 32
                per_pass, cnt = slope_per_pass(
                    dev, lay.chunk, pad_rows, fn, r1=2, r2=10,
                    measurements=3,
                )
                gbs = lay.chunk * lay.lanes / per_pass / 1e9
                print(json.dumps({
                    "probe": f"swar_slope_{name}", "pattern": pattern,
                    "ic": ic, "filtered": filtered, "unroll": unroll,
                    "gbs": round(gbs, 1), "count": int(cnt),
                }), flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("which", choices=["exact", "slope"])
    ap.add_argument("--interpret", action="store_true",
                    help="force interpret mode (CI smoke; CPU)")
    ap.add_argument("--mb", type=int, default=None)
    ap.add_argument("--unrolls", default="32,16,8")
    args = ap.parse_args()

    import jax

    print("backend:", jax.default_backend(), flush=True)
    if args.which == "exact":
        return 1 if probe_exact(args.interpret, args.mb or
                                (8 if args.interpret else 32)) else 0
    probe_slope(args.mb or 64, [int(u) for u in args.unrolls.split(",")])
    return 0


if __name__ == "__main__":
    sys.exit(main())

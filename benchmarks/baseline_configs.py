"""BASELINE.json config suite: the 5 headline scan scenarios.

The reference publishes no numbers (SURVEY.md §6); BASELINE.json instead
pins 5 workload shapes.  Real corpora (enwik9, Common Crawl WET, NASA-HTTP,
PCAP dumps) are not fetchable in this environment (zero egress), so each
config synthesizes a statistically similar corpus and measures the engine
end-to-end — device scan + sparse fetch + host stitching, i.e. what a user
gets, not just kernel time.

    python benchmarks/baseline_configs.py [--size-mb 64] [--configs 1,3]
        [--backend device|cpu] [--check]

Prints one JSON line per config:
    {"config": N, "name": "...", "value": GB/s, "unit": "GB/s",
     "matched_lines": M, "mode": "..."}

--check additionally greps the WHOLE synthetic corpus (every split) with an
independent oracle — system ``grep -F -f`` for pattern sets, Python re per
line otherwise — and asserts the engine's matched lines agree exactly
(recall check, Hyperscan-equivalent semantics at line granularity).
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

# Runnable as `python benchmarks/...` / `python bench.py` from anywhere:
# the repo root joins the FRONT of sys.path unconditionally, so the
# checkout being benchmarked always wins over any installed copy of the
# package.  (Repeated per script by necessity — a shared helper could not
# be imported before the path is fixed.)
_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))
import time

import numpy as np

from distributed_grep_tpu.ops.engine import GrepEngine

WORDS = (
    "the of and to in a is that for it as was with be by on not he this are "
    "at from or have an they which one you were all her she there would their "
    "we him been has when who will no more if out so up said what its about "
    "than into them can only other time new some could these two may first "
    "then do any like my now over such our man me even most made after also "
    "did many fff needle volcano anarchism philosophy wikipedia"
).split()


def _words_text(size: int, seed: int, line_words=12) -> bytes:
    """English-like filler (enwik/WET-like: words, spaces, newlines)."""
    rng = np.random.default_rng(seed)
    out, n = [], 0
    while n < size:
        k = int(rng.integers(3, line_words * 2))
        line = b" ".join(WORDS[i].encode() for i in rng.integers(0, len(WORDS), k))
        out.append(line)
        n += len(line) + 1
    return b"\n".join(out)[:size]


def _log_text(size: int, seed: int) -> bytes:
    """NASA-HTTP-style access log lines."""
    rng = np.random.default_rng(seed)
    hosts = [f"host{i}.example.com".encode() for i in range(100)]
    paths = [b"/images/logo", b"/shuttle/missions", b"/cgi-bin/query",
             b"/images/KSC-small.gif", b"/history/apollo", b"/icons/menu.gif"]
    out, n = [], 0
    while n < size:
        h = hosts[int(rng.integers(0, len(hosts)))]
        p = paths[int(rng.integers(0, len(paths)))]
        code = int(rng.integers(200, 505))
        sz = int(rng.integers(0, 100000))
        line = b'%s - - [01/Jul/1995:00:00:%02d -0400] "GET %s HTTP/1.0" %d %d' % (
            h, int(rng.integers(0, 60)), p, code, sz)
        out.append(line)
        n += len(line) + 1
    return b"\n".join(out)[:size]


def _binary_payload(size: int, seed: int) -> bytes:
    """PCAP-payload-like bytes: mixed binary with ~120-byte 'packets' split
    by '\\n' records (line semantics keep grep's contract meaningful)."""
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, size=size, dtype=np.uint8)
    data[data == 0x0A] = 0x0B  # strip accidental newlines...
    data[rng.integers(0, size, size=size // 120)] = 0x0A  # ...then add records
    return data.tobytes()


def _rand_literals(n: int, lo: int, hi: int, seed: int, alphabet=None) -> list[str]:
    rng = np.random.default_rng(seed)
    pats = set()
    while len(pats) < n:
        k = int(rng.integers(lo, hi + 1))
        if alphabet is None:
            chars = rng.integers(97, 123, size=k)  # a-z
        else:
            chars = rng.choice(alphabet, size=k)
        pats.add("".join(chr(c) for c in chars))
    return sorted(pats)


def _inject(data: bytes, needles: list[bytes], count: int, seed: int) -> bytes:
    """Overwrite `count` random positions with needles (away from edges)."""
    arr = np.frombuffer(data, dtype=np.uint8).copy()
    rng = np.random.default_rng(seed)
    for pos in rng.integers(0, len(arr) - 64, size=count):
        nd = needles[int(rng.integers(0, len(needles)))]
        arr[pos : pos + len(nd)] = np.frombuffer(nd, dtype=np.uint8)
    out = arr
    return out.tobytes()


# --------------------------------------------------------------- the configs

def config_1(size: int):
    """literal substring grep on enwik8 (single file)."""
    data = _words_text(size, seed=1)
    return dict(name="enwik8_literal", pattern="volcano", data=[data],
                engine_kw={})


def config_2(size: int):
    """single PCRE alternation regex on enwik9, 8 input splits."""
    split = max(size // 8, 1 << 16)
    datas = [_words_text(split, seed=20 + i) for i in range(8)]
    return dict(name="enwik9_alternation_8splits",
                pattern="(volcano|anarchism|philosophy|needle|wikipedia"
                        "|quantum|zeppelin|obsidian)",
                data=datas, engine_kw={})


def config_3(size: int):
    """1k-literal multi-pattern set (Aho-Corasick) on Common Crawl WET."""
    pats = _rand_literals(1000, 6, 12, seed=3)
    data = _inject(_words_text(size, seed=30),
                   [p.encode() for p in pats[:50]], count=max(size // 65536, 4),
                   seed=31)
    return dict(name="wet_1k_aho_corasick", patterns=pats, data=[data],
                engine_kw={})


def config_4(size: int):
    """case-insensitive + bounded-repeat regex on NASA-HTTP access logs."""
    data = _log_text(size, seed=4)
    return dict(name="nasa_logs_ci_bounded_repeat",
                pattern=r"get /[a-z0-9/.-]{4,24}\.gif",
                data=[data], engine_kw={"ignore_case": True})


def config_5(size: int, n_patterns: int = 10_000):
    """10k-pattern Snort/Suricata ruleset scan on PCAP payloads."""
    alphabet = np.arange(1, 256)
    alphabet = alphabet[alphabet != 0x0A]
    pats = _rand_literals(n_patterns, 5, 9, seed=5, alphabet=alphabet)
    data = _inject(_binary_payload(size, seed=50),
                   [p.encode("latin-1") for p in pats[:100]],
                   count=max(size // 65536, 4), seed=51)
    return dict(name="pcap_10k_ruleset",
                patterns=[p.encode("latin-1") for p in pats],
                data=[data], engine_kw={})


CONFIGS = {1: config_1, 2: config_2, 3: config_3, 4: config_4, 5: config_5}


# -------------------------------------------------------- slope-mode timing

def slope_gbps(eng: GrepEngine, data: bytes) -> tuple[float, str] | None:
    """Device-resident scan throughput via the slope method (chained passes
    over i-dependent windows inside one jit; per-pass time from the rep-count
    slope).  Excludes host<->device transfer — the honest per-chip kernel
    number when the host link is slow (the axon tunnel here runs at ~MB/s;
    on production hardware the e2e default is the fairer figure).  Returns
    (GB/s, engine_label) or None when the engine has no device path."""
    import jax
    import jax.numpy as jnp

    from distributed_grep_tpu.ops import layout as layout_mod
    from distributed_grep_tpu.ops import pallas_nfa, pallas_scan, scan_jnp
    from distributed_grep_tpu.utils.slope import (
        pallas_fdr_setup,
        pallas_nfa_setup,
        pallas_shift_and_setup,
        slope_per_pass,
    )

    if eng.mode not in ("shift_and", "nfa", "dfa", "fdr"):
        return None

    use_pallas_sa = (
        eng.mode == "shift_and"
        and pallas_scan.available()
        and pallas_scan.eligible(eng.shift_and)
    )
    use_pallas_nfa = (
        eng.mode == "nfa"
        and pallas_scan.available()
        and pallas_nfa.eligible(eng.glushkov)
    )
    use_pallas_fdr = eng.mode == "fdr" and pallas_scan.available() and eng.fdr
    if use_pallas_sa:
        sa_model = eng._sa_filtered or eng.shift_and
        n_checked = sum(1 for r in sa_model.sym_ranges if r)
        label = ("pallas_shift_and" if sa_model is eng.shift_and
                 else f"pallas_shift_and_filt{n_checked}")
        dev, chunk, pad_rows, scan = pallas_shift_and_setup(data, sa_model)
    elif use_pallas_nfa:
        label = ("pallas_nfa_filt" if getattr(eng, "_nfa_filter", False)
                 else "pallas_nfa")
        dev, chunk, pad_rows, scan = pallas_nfa_setup(data, eng.glushkov)
    elif use_pallas_fdr:
        label = f"pallas_fdr_x{len(eng.fdr.banks)}"
        if eng.ignore_case:
            data = bytes(data).lower()
        dev, chunk, pad_rows, scan = pallas_fdr_setup(data, eng.fdr)
    else:
        lay = layout_mod.choose_layout(len(data), target_lanes=4096, min_chunk=64)
        arr = layout_mod.to_device_array(data, lay)
        pad_rows = 8
        chunk = lay.chunk
        if eng.mode == "shift_and":
            label = "xla_shift_and"
            b_table = jnp.asarray(eng.shift_and.b_table)
            match_bit = jnp.uint32(eng.shift_and.match_bit)

            def scan(win):
                return scan_jnp._shift_and_core(win, b_table, match_bit)
        else:
            banks = eng._device_tables()
            label = f"{'stride' if banks[0][0] == 'stride' else 'dfa'}_x{len(banks)}"

            def scan(win):
                total = jnp.int32(0)
                for kind, bank in banks:
                    core = (scan_jnp._dfa_stride_core if kind == "stride"
                            else scan_jnp._dfa_scan_core)
                    total = total + jnp.count_nonzero(core(win, *bank))
                return total

        pad = np.full((pad_rows,) + arr.shape[1:], 0x0A, dtype=np.uint8)
        dev = jax.device_put(jnp.asarray(np.concatenate([arr, pad], axis=0)))
    # A timing failure (e.g. non-positive slope from noise) propagates as a
    # RuntimeError — main() reports it as an error rather than mislabeling
    # it "no device path".  Pallas passes are fast enough that low rep
    # counts drown in tunnel noise — give them a longer chain.
    if label.startswith("pallas"):
        # Scale the chain so it covers >~1.5 GB regardless of split size —
        # an 8 MB split (config 2) needs ~200 reps before the slope rises
        # above the tunnel's run-to-run noise.
        r2 = min(256, max(40, int(1.5e9 / max(len(data), 1))))
        r2 += r2 % 2
        r1 = max(8, r2 // 5 + (r2 // 5) % 2)
        per_pass, _ = slope_per_pass(
            dev, chunk, pad_rows, scan, r1=r1, r2=r2, measurements=3
        )
    else:
        per_pass, _ = slope_per_pass(dev, chunk, pad_rows, scan, measurements=3)
    return len(data) / 1e9 / per_pass, label


# ------------------------------------------------------------------- driver

def _oracle_lines(spec, data: bytes) -> set[int]:
    pats = spec.get("patterns")
    if pats is not None:
        # system grep -nF -f: independent oracle that stays fast at 10k
        # patterns (a Python re alternation is O(set) per position)
        import os
        import subprocess
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".pats", delete=False) as pf, \
             tempfile.NamedTemporaryFile(suffix=".dat", delete=False) as df:
            pf.write(b"\n".join(
                p if isinstance(p, bytes) else p.encode() for p in pats) + b"\n")
            df.write(data)
            pnames = (pf.name, df.name)
        try:
            args = ["grep", "-naF"]
            if spec["engine_kw"].get("ignore_case"):
                args.append("-i")
            # LC_ALL=C: byte semantics — a UTF-8 locale makes grep skip
            # matches starting mid-multibyte-sequence in binary corpora
            out = subprocess.run(
                [*args, "-f", pnames[0], pnames[1]],
                capture_output=True,
                check=False,
                env={**os.environ, "LC_ALL": "C"},
            )
            if out.returncode > 1:  # 0 = matches, 1 = none, >1 = error
                raise RuntimeError(f"grep oracle failed: {out.stderr[:200]!r}")
        finally:
            for n in pnames:
                os.unlink(n)
        # split on '\n' only: bytes.splitlines also splits on '\r', which
        # binary corpora contain mid-line
        return {
            int(line.split(b":", 1)[0]) for line in out.stdout.split(b"\n") if line
        }
    flags = re.IGNORECASE if spec["engine_kw"].get("ignore_case") else 0
    rx = re.compile(spec["pattern"].encode(), flags)
    return {i for i, line in enumerate(data.split(b"\n"), 1) if rx.search(line)}


def run_config(
    num: int,
    size: int,
    backend: str,
    check: bool,
    timing: str = "e2e",
    **config_kwargs,
) -> dict:
    spec = CONFIGS[num](size, **config_kwargs)
    t0 = time.perf_counter()
    eng = GrepEngine(
        spec.get("pattern"),
        patterns=spec.get("patterns"),
        backend=backend,
        **spec["engine_kw"],
    )
    compile_s = time.perf_counter() - t0
    datas = spec["data"]

    if timing == "slope":
        got = slope_gbps(eng, datas[0])
        if got is None:
            return {"config": num, "name": spec["name"],
                    "error": f"no device path for mode {eng.mode}"}
        gbps, label = got
        out = {
            "config": num,
            "name": spec["name"],
            "value": round(gbps, 3),
            "unit": "GB/s",
            "timing": "slope(device-resident)",
            "engine": label,
            "mode": eng.mode,
            "banks": len(eng.tables),
            "compile_s": round(compile_s, 2),
            "bytes": len(datas[0]),
        }
    else:
        # Warm with a full-size scan: jit specializes on the (chunk, lanes)
        # layout, so a truncated warmup would leave compilation inside the
        # timed region.
        eng.scan(datas[0])

        total_bytes = sum(len(d) for d in datas)
        matched = 0
        t0 = time.perf_counter()
        for d in datas:
            res = eng.scan(d)
            matched += int(res.matched_lines.size)
        dt = time.perf_counter() - t0

        out = {
            "config": num,
            "name": spec["name"],
            "value": round(total_bytes / 1e9 / dt, 3),
            "unit": "GB/s",
            "timing": "e2e",
            "matched_lines": matched,
            "mode": eng.mode,
            "banks": len(eng.tables),
            "compile_s": round(compile_s, 2),
            "bytes": total_bytes,
        }
    if check:
        # Full-corpus recall check (every split) against the independent
        # oracle — system grep for sets, Python re otherwise.  VERDICT
        # round-1 weak #5: a 1 MB slice was not enough to back the
        # "Hyperscan-equivalent recall" claim; this is the whole corpus.
        mism = []
        for i, d in enumerate(datas):
            got = set(eng.scan(d).matched_lines.tolist())
            want = _oracle_lines(spec, d)
            if got != want:
                mism.append(f"split{i}:+{len(got - want)}-{len(want - got)}")
        out["check"] = "ok" if not mism else "MISMATCH " + ",".join(mism)
        out["check_bytes"] = sum(len(d) for d in datas)
        if mism:
            out["value"] = 0.0
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=float, default=64)
    ap.add_argument("--configs", default="1,2,3,4,5")
    ap.add_argument("--backend", default="device", choices=["device", "cpu"])
    ap.add_argument("--timing", default="e2e", choices=["e2e", "slope"],
                    help="e2e: engine.scan wall time incl. transfers; "
                         "slope: device-resident chained passes (per-chip "
                         "kernel throughput, for slow-link environments)")
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--patterns-5", type=int, default=10_000,
                    help="pattern count for config 5")
    args = ap.parse_args()

    size = int(args.size_mb * 1e6)
    rc = 0
    for num in (int(x) for x in args.configs.split(",")):
        kw = {"n_patterns": args.patterns_5} if num == 5 else {}
        try:
            result = run_config(num, size, args.backend, args.check, args.timing, **kw)
        except Exception as e:  # noqa: BLE001
            result = {"config": num, "error": f"{type(e).__name__}: {e}"}
            rc = 1
        print(json.dumps(result), flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())

"""Probe the Mosaic lane-gather compile ceiling past MAX_GATHERS=40.

CLAUDE.md hardware facts: 40 gathers/byte compile and run at unroll 4
and 8 (round-4 probe); 48 was unprobed and gated off.  The FDR kernel
(ops/pallas_fdr._kernel) is plan-generic — a check's domain is just its
subtable count — so this probe hand-builds synthetic m=6 banks whose
checks sum to 44/48/56/64 gathers (fillers at D=1024, i.e. 8 subtables,
beyond the production DOMAINS=(128,256,512)), compiles them for real,
verifies candidates bit-exact against models/fdr.reference_candidates,
and slope-times throughput.

    PYTHONPATH=/root/repo:/root/.axon_site \
        python benchmarks/probe_gather_ceiling.py [--targets 44,48,56,64]

If 48+ compiles and runs exactly at both production unrolls, MAX_GATHERS
can be raised (models/fdr.py) and D=1024 considered for the tuner's
domain menu for sets dense enough that halving per-check fp is worth 2x
gather cost.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

import numpy as np

_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
sys.path.insert(0, str(_root))

from distributed_grep_tpu.models.fdr import FdrBank, reference_candidates  # noqa: E402

M = 6


def synth_bank(rng: np.random.Generator, total_gathers: int) -> FdrBank:
    """m=6 bank: a D=128 check (slot 5, fam 0) + fillers from
    D in {1024, 512, 256, 128} chosen to hit the target gather count
    exactly.  Tables are uniform random (bit density 0.5): with 12
    checks the per-byte candidate rate is ~32 * 0.5^12 ~ 8e-3 — a real
    nonzero stream for the bit-exact compare, not all-zeros (which would
    let an under-reporting kernel pass) and not every-byte."""
    slots = [(k, 0) for k in range(M - 2, -1, -1)] + [
        (k, 1) for k in range(M - 1, -1, -1)
    ]
    checks = [(M - 1, 0, 128)]
    need = total_gathers - 1
    for slot, fam in slots:
        if need <= 0:
            break
        d = 1024 if need >= 8 else 128 * need
        checks.append((slot, fam, d))
        need -= d // 128
    if need:
        raise ValueError(f"cannot reach {total_gathers} gathers with m={M}")

    tables = tuple(
        rng.integers(0, 2 ** 32, size=d, dtype=np.uint32) for _, _, d in checks
    )
    return FdrBank(m=M, checks=tuple(checks), tables=tables,
                   patterns=[b"<synthetic>"], fp_per_byte=0.0)


def check_exact(bank: FdrBank, unroll: int) -> tuple[bool, float, str]:
    """Compile + run a small real-Mosaic scan; compare every lane stripe
    against the NumPy reference.  Returns (ok, compile_seconds, note)."""
    import jax.numpy as jnp

    from distributed_grep_tpu.ops import layout as layout_mod
    from distributed_grep_tpu.ops import pallas_scan
    from distributed_grep_tpu.ops.pallas_fdr import (
        _fdr_pallas,
        bank_device_tables,
        kernel_plan,
    )
    from distributed_grep_tpu.ops.pallas_scan import _unpack_words_to_lane_bits

    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=2 * 1024 * 1024, dtype=np.uint8).tobytes()
    lay = layout_mod.choose_layout(
        len(data), target_lanes=4096, min_chunk=512,
        lane_multiple=pallas_scan.LANES_PER_BLOCK, chunk_multiple=512,
    )
    arr = layout_mod.to_device_array(data, lay)
    tiles = pallas_scan.as_tiles(arr, lay.lanes // pallas_scan.LANES_PER_BLOCK)
    tabs = jnp.asarray(bank_device_tables(bank))
    t0 = time.time()
    try:
        words = _fdr_pallas(
            tiles, tabs, m=bank.m, plan=kernel_plan(bank), chunk=lay.chunk,
            lane_blocks=lay.lanes // pallas_scan.LANES_PER_BLOCK,
            interpret=False, unroll=unroll,
        ).block_until_ready()
    except Exception as e:
        return False, time.time() - t0, "FAIL: " + str(e).replace("\n", " ")[:200]
    dt = time.time() - t0
    got = _unpack_words_to_lane_bits(np.asarray(words), lay.chunk, lay.lanes)
    arr_np = np.asarray(arr)
    want = np.zeros((lay.chunk, lay.lanes), dtype=bool)
    for lane in range(lay.lanes):
        ends = reference_candidates(bank, bytes(arr_np[:, lane]))
        want[(ends - 1).astype(np.int64), lane] = True
    ok = np.array_equal(got, np.packbits(want, axis=1, bitorder="little"))
    return ok, dt, "exact" if ok else "MISMATCH"


def slope_gbps(bank: FdrBank, unroll: int, mb: int) -> float:
    from distributed_grep_tpu.ops.pallas_fdr import (
        _fdr_pallas,
        bank_device_tables,
        kernel_plan,
    )
    from distributed_grep_tpu.utils.slope import _pallas_device_setup, slope_per_pass
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, size=mb * 1024 * 1024, dtype=np.uint8).tobytes()
    dev, lay, lane_blocks, pad_rows = _pallas_device_setup(data, 8192)
    tabs = jnp.asarray(bank_device_tables(bank))
    plan = kernel_plan(bank)

    def scan(win):
        return _fdr_pallas(
            win, tabs, m=bank.m, plan=plan, chunk=lay.chunk,
            lane_blocks=lane_blocks, interpret=False, unroll=unroll,
        )

    sec, _count = slope_per_pass(dev, lay.chunk, pad_rows, scan)
    return lay.chunk * lay.lanes / sec / 1e9


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--targets", default="44,48,56,64")
    ap.add_argument("--unrolls", default="4,8")
    ap.add_argument("--mb", type=int, default=32, help="corpus MiB for timing")
    args = ap.parse_args()

    import jax

    print("backend:", jax.default_backend(), jax.devices(), flush=True)
    rng = np.random.default_rng(4242)
    failures = 0
    for target in [int(t) for t in args.targets.split(",")]:
        bank = synth_bank(rng, target)
        assert bank.total_gathers == target, bank.total_gathers
        for unroll in [int(u) for u in args.unrolls.split(",")]:
            ok, dt, note = check_exact(bank, unroll)
            if not ok:
                failures += 1
                print(f"gathers={target} unroll={unroll}: {note} "
                      f"({dt:.1f}s)", flush=True)
                continue
            gbps = slope_gbps(bank, unroll, args.mb)
            print(f"gathers={target} unroll={unroll}: compile {dt:.1f}s, "
                  f"{note}, {gbps:.2f} GB/s", flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())

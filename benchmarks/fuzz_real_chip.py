"""Differential fuzz on the REAL TPU kernels (no interpret mode).

The CI fuzz families (tests/test_fuzz_recall.py) run the Pallas kernels in
interpret mode; the real-Mosaic validation otherwise rests on the five
fixed BASELINE configs.  This driver closes the gap with pattern
DIVERSITY on the real chip: per family it draws random patterns, scans a
~2 MB corpus with the production engine (device backend, real Mosaic
compile), and checks matched lines exactly against a host `re`/substring
oracle.  Compiles are shared across patterns (kernel constants are
operands), so a seed costs ~1.5 s through the tunnel.

    PYTHONPATH=/root/repo:/root/.axon_site \
        python benchmarks/fuzz_real_chip.py [--seeds 40] [--start 0]

Prints one line per family; any failure prints the seed + pattern and
exits 1.
"""

from __future__ import annotations

import argparse
import re
import sys
from pathlib import Path

import numpy as np

_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
sys.path.insert(0, str(_root))

from distributed_grep_tpu.ops.engine import GrepEngine  # noqa: E402

WORDS = (
    "alpha bravo charlie delta echo foxtrot golf hotel india juliet kilo "
    "lima mike november oscar papa quebec romeo sierra tango uniform victor "
    "whiskey xray yankee zulu one two three four five six seven eight nine"
).split()
ALPHA = "abcdefghijklmnopqrstuvwxyz"


def make_corpus(rng, injections: list, n_lines=30000) -> bytes:
    """Injections land at line ENDS by default; a ("start", payload)
    tuple plants the payload at the line START instead (the position a
    '^' branch actually gates — round-5 mid_anchor family)."""
    lines = [
        " ".join(WORDS[i] for i in rng.integers(0, len(WORDS), int(rng.integers(3, 12)))).encode()
        for _ in range(n_lines)
    ]
    for inj in injections:
        at_start = isinstance(inj, tuple)
        payload = inj[1] if at_start else inj
        for pos in rng.integers(0, n_lines, 20):
            if at_start:
                lines[int(pos)] = payload + b" " + lines[int(pos)]
            else:
                lines[int(pos)] = lines[int(pos)] + b" " + payload
    return b"\n".join(lines) + b"\n"


def re_oracle(pattern: bytes, flags=0):
    """Family oracle: matched 1-based line numbers per host `re`."""
    def want(data: bytes) -> list[int]:
        pat = re.compile(pattern, flags)
        return [i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
                if pat.search(ln)]

    return want


def rand_word(rng, lo=3, hi=9) -> str:
    return "".join(ALPHA[i] for i in rng.integers(0, 26, int(rng.integers(lo, hi))))


# Each family: seed -> (engine_kwargs, want_fn(data)->line list, injections)
def fam_literal(rng):
    w = rand_word(rng)
    return dict(pattern=w), re_oracle(re.escape(w).encode()), [w.encode()]


def fam_class_seq(rng):
    parts, inj = [], []
    for _ in range(int(rng.integers(3, 8))):
        if rng.random() < 0.4:
            a = int(rng.integers(0, 24))
            parts.append(f"[{ALPHA[a]}-{ALPHA[a + 2]}]")
            inj.append(ALPHA[a + 1])
        else:
            c = ALPHA[int(rng.integers(0, 26))]
            parts.append(c)
            inj.append(c)
    pat = "".join(parts)
    return dict(pattern=pat), re_oracle(pat.encode()), ["".join(inj).encode()]


def fam_alternation(rng):
    ws = [rand_word(rng) for _ in range(int(rng.integers(2, 6)))]
    pat = "(" + "|".join(ws) + ")"
    return dict(pattern=pat), re_oracle(pat.encode()), [w.encode() for w in ws[:2]]


def fam_ignore_case(rng):
    w = rand_word(rng)
    mixed = "".join(c.upper() if rng.random() < 0.5 else c for c in w)
    return (dict(pattern=w, ignore_case=True),
            re_oracle(re.escape(w).encode(), re.IGNORECASE), [mixed.encode()])


def fam_bounded_repeat(rng):
    a, b = rand_word(rng, 2, 4), rand_word(rng, 2, 4)
    m = int(rng.integers(1, 4))
    n = m + int(rng.integers(1, 30))
    pat = f"{a}[a-z ]{{{m},{n}}}{b}"
    inj = (a + "x" * m + b).encode()
    return dict(pattern=pat), re_oracle(pat.encode()), [inj]


def fam_literal_set(rng):
    ws = sorted({rand_word(rng) for _ in range(int(rng.integers(20, 120)))})
    pat = b"|".join(re.escape(w).encode() for w in ws)
    return dict(patterns=list(ws)), re_oracle(pat), [w.encode() for w in ws[:3]]


def fam_pairset(rng):
    # 2-byte members: rare enough in the word corpus to stay under the
    # device density ceiling, so draws exercise the pairset KERNEL
    # (1-char members route native by density — separately covered)
    ws = sorted({rand_word(rng, 2, 3) for _ in range(int(rng.integers(3, 10)))})
    pat = b"|".join(re.escape(w).encode() for w in ws)
    return dict(patterns=list(ws)), re_oracle(pat), []


def fam_approx(rng):
    # agrep k=1: oracle is the host recurrence (models/approx
    # line_matches — CI pins IT against an independent edit-distance DP),
    # so this checks device kernel == host model on the real chip
    w = rand_word(rng, 6, 11)
    mutated = list(w)
    mutated[int(rng.integers(0, len(w)))] = ALPHA[int(rng.integers(0, 26))]

    def want(data: bytes) -> list[int]:
        from distributed_grep_tpu.models.approx import (
            line_matches,
            try_compile_approx,
        )

        model = try_compile_approx(w, 1)
        return [i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
                if line_matches(model, ln)]

    return dict(pattern=w, max_errors=1), want, [w.encode(), "".join(mutated).encode()]


def fam_dollar_anchor(rng):
    # round-5 device filter: '$'-anchored single pattern rides the NFA
    # kernel with the '$' dropped (models/nfa.compile_device_filter) and
    # every candidate line host-confirmed.  Injections plant both true
    # matches (word at line end) and near-misses (word mid-line) so the
    # confirm pass has false positives to reject on every draw.
    w = rand_word(rng, 4, 9)
    pat = w + "$"
    return (dict(pattern=pat),
            re_oracle(re.escape(w).encode() + b"$"),
            [w.encode(), w.encode() + b"qq"])


def fam_overcap_literal(rng):
    # round-5 device filter: a literal past the 128-Glushkov-position
    # kernel cap runs prefix-truncated on the device; host confirm
    # restores exactness.  Near-miss = shared long prefix, different
    # tail — the device filter flags it, the confirm must drop it.
    n = int(rng.integers(130, 200))
    w = "".join(ALPHA[i] for i in rng.integers(0, 26, n))
    near = (w[:-4] + rand_word(rng, 4, 5)).encode()
    return dict(pattern=w), re_oracle(re.escape(w).encode()), [w.encode(), near]


def fam_mid_anchor(rng):
    # round-5: mid-pattern anchors ('(^a|b)c') are in the subset compiler
    # (models/dfa ls_eps/eol_eps); on the device they ride the
    # anchor-stripped NFA filter with host confirm.  Inject line-start
    # hits for the '^' branch, plain hits for the other, and mid-line
    # decoys (needle preceded by a byte) the anchors must veto.
    a, b, c = rand_word(rng, 2, 5), rand_word(rng, 2, 5), rand_word(rng, 1, 4)
    if rng.random() < 0.5:
        pat = f"(^{a}|{b}){c}"
    else:
        pat = f"{a}({b}$|{c})"
    inj = [
        ("start", (a + c).encode()),   # true '^' hit: a+c at line start
        ("start", (a + b).encode()),   # '$'-variant line-start decoy
        f"q{a}{c}".encode(),           # mid/end decoys the anchors veto
        (a + b).encode(),              # true '$' hit at line end
        (b + c).encode(),              # unanchored-branch hit anywhere
    ]
    return dict(pattern=pat), re_oracle(pat.encode()), inj


def fam_posix_classes(rng):
    # round-5: POSIX bracket classes compile into the automaton subset
    # (re can't host them); oracle = re of the expanded form, which the
    # CLI fuzz pins against GNU.  Drawn with literal tails/repeats so
    # the engine routes across shift_and/nfa/dfa modes.
    from distributed_grep_tpu.models.dfa import expand_posix_classes

    names = ["digit", "alpha", "upper", "lower", "alnum", "punct", "xdigit"]
    nm = names[int(rng.integers(0, len(names)))]
    w = rand_word(rng, 2, 5)
    pat = {
        0: lambda: f"{w}[[:{nm}:]]",
        1: lambda: f"[[:{nm}:]]{{2,4}}{w}",
        2: lambda: f"{w}[^[:{nm}:]]{w[:2]}",
    }[int(rng.integers(0, 3))]()
    inj = [f"{w}7".encode(), f"{w}Q".encode(), f"99{w}".encode(),
           f"{w}.{w[:2]}".encode()]
    return dict(pattern=pat), re_oracle(expand_posix_classes(pat).encode()), inj


def fam_word_boundary(rng):
    # round-5: \b/\B strip for the device NFA filter (superset), with
    # candidate lines re-confirmed under the original semantics.
    # Injections plant word-bounded hits and glued decoys the confirm
    # must reject.
    w = rand_word(rng, 3, 7)
    pat = {0: rf"\b{w}\b", 1: rf"\b{w}", 2: rf"{w}\B"}[int(rng.integers(0, 3))]
    inj = [w.encode(), f"x{w}".encode(), f"{w}x9".encode(), f".{w}.".encode()]
    return dict(pattern=pat), re_oracle(pat.encode()), inj


FAMILIES = {
    "literal": fam_literal,
    "class_seq": fam_class_seq,
    "alternation": fam_alternation,
    "ignore_case": fam_ignore_case,
    "bounded_repeat": fam_bounded_repeat,
    "literal_set": fam_literal_set,
    "pairset": fam_pairset,
    "approx": fam_approx,
    "dollar_anchor": fam_dollar_anchor,
    "overcap_literal": fam_overcap_literal,
    "mid_anchor": fam_mid_anchor,
    "word_boundary": fam_word_boundary,
    "posix_classes": fam_posix_classes,
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=40)
    ap.add_argument("--start", type=int, default=0)
    ap.add_argument("--families", default=None)
    args = ap.parse_args()
    fams = FAMILIES
    if args.families:
        fams = {k: FAMILIES[k] for k in args.families.split(",")}
    from collections import Counter

    for name, gen in fams.items():
        ok = 0
        modes: Counter = Counter()
        for seed in range(args.start, args.start + args.seeds):
            rng = np.random.default_rng(900_000 + seed)
            kw, want_fn, inj = gen(rng)
            data = make_corpus(rng, inj)
            eng = GrepEngine(backend="device", device_min_bytes=0, **kw)
            got = eng.scan(data).matched_lines.tolist()
            want = want_fn(data)
            if got != want:
                print(f"FAIL {name} seed={seed} kw={kw} mode={eng.mode} "
                      f"got {len(got)} want {len(want)} "
                      f"diff_lines={sorted(set(got) ^ set(want))[:5]}")
                return 1
            ok += 1
            modes[eng.mode] += 1
        print(f"{name}: {ok}/{args.seeds} ok (modes {dict(modes)})", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

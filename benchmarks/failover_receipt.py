"""Failover SLO receipt: SIGKILL the active daemon, time the takeover.

ISSUE 17's acceptance bar: an active+standby pair over one work root,
the active SIGKILLed mid-job, and the standby's promotion measured on
the REAL surface — the walls that feed the
``dgrep_daemon_failover_seconds`` histogram and the fleet timeline:

* ``failover_s``      — the promoted daemon's own detection→serving
                        clock, read back from daemon.jsonl's
                        ``promoted`` line (the histogram's sample);
* ``kill_to_active_s``— external wall from SIGKILL to the standby
                        answering /status role "active";
* ``active_to_first_progress_s`` — promotion to the first map-progress
                        advance the resumed job shows (assignment +
                        completion through the replayed scheduler).

Prints exactly ONE JSON line.  ``--check`` additionally gates: job
state "done" and ``failover_s`` > 0.  Pure control plane — the daemon
subprocesses own the jax stack; this driver only speaks HTTP and reads
daemon.jsonl.

    python benchmarks/failover_receipt.py [--files 6] [--file-kb 64]
        [--ttl-s 2.0] [--check]

Real-cluster recipe: same shape with the standby on a second host and
`dgrep worker --addr active,standby` fleets instead of --workers; the
histogram then aggregates over real failovers via `dgrep top` or any
Prometheus scrape of the promoted daemon's /metrics.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

from distributed_grep_tpu.runtime.daemon_log import DaemonLog  # noqa: E402
from distributed_grep_tpu.utils.config import JobConfig  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http_json(method: str, url: str, body: bytes | None = None,
               timeout: float = 10.0) -> dict:
    req = urllib.request.Request(url, data=body, method=method)
    if body is not None:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _serve(work_root: Path, port: int, workers: int, ttl_s: float,
           standby: bool, log_path: Path) -> subprocess.Popen:
    env = {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        "PYTHONPATH": str(_root),
        "JAX_PLATFORMS": "cpu",
        "DGREP_NO_CALIBRATE": "1",
        "DGREP_LOG": "WARNING",
        "DGREP_LEASE_TTL_S": str(ttl_s),
    }
    args = [sys.executable, "-m", "distributed_grep_tpu", "serve",
            "--host", "127.0.0.1", "--port", str(port),
            "--work-root", str(work_root), "--workers", str(workers)]
    if standby:
        args.append("--standby")
    return subprocess.Popen(args, stdout=subprocess.DEVNULL,
                            stderr=open(log_path, "wb"), env=env)


def _wait_status(port: int, deadline: float, want_role: str | None = None
                 ) -> dict:
    while time.monotonic() < deadline:
        try:
            st = _http_json("GET", f"http://127.0.0.1:{port}/status",
                            timeout=5.0)
            if st.get("service") and (want_role is None
                                      or st.get("role") == want_role):
                return st
        except OSError:
            pass
        time.sleep(0.05)
    raise TimeoutError(f"daemon on :{port} never answered"
                       + (f" role={want_role}" if want_role else ""))


def _build_corpus(root: Path, files: int, file_kb: int) -> list[str]:
    root.mkdir(parents=True, exist_ok=True)
    out = []
    for i in range(files):
        p = root / f"part{i:02d}.txt"
        line = f"alpha beta hello gamma {i} filler text line\n"
        miss = "nothing to see on this line at all\n"
        n = max(1, (file_kb * 1024) // len(line))
        p.write_text((line + miss * 3) * (n // 4 + 1))
        out.append(str(p))
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--files", type=int, default=6)
    ap.add_argument("--file-kb", type=int, default=64)
    ap.add_argument("--ttl-s", type=float, default=2.0)
    ap.add_argument("--check", action="store_true",
                    help="gate: job done and failover_s > 0")
    args = ap.parse_args()

    tmp = Path(tempfile.mkdtemp(prefix="dgrep-failover-"))
    work_root = tmp / "svc"
    work_root.mkdir()
    inputs = _build_corpus(tmp / "corpus", args.files, args.file_kb)

    a_port, b_port = _free_port(), _free_port()
    active = _serve(work_root, a_port, workers=1, ttl_s=args.ttl_s,
                    standby=False, log_path=tmp / "active.log")
    standby = _serve(work_root, b_port, workers=1, ttl_s=args.ttl_s,
                     standby=True, log_path=tmp / "standby.log")
    result: dict = {"benchmark": "failover_receipt", "files": args.files,
                    "file_kb": args.file_kb, "ttl_s": args.ttl_s}
    try:
        _wait_status(a_port, time.monotonic() + 60, "active")
        _wait_status(b_port, time.monotonic() + 60, "standby")

        cfg = JobConfig(
            input_files=inputs,
            application="distributed_grep_tpu.apps.grep_tpu",
            app_options={"pattern": "hello", "backend": "cpu"},
            n_reduce=2,
            task_timeout_s=5.0,
            work_dir=str(tmp / "sub"),
        )
        jid = _http_json("POST", f"http://127.0.0.1:{a_port}/jobs",
                         cfg.to_json().encode())["job_id"]
        # catch the kill mid-map so the promotion resumes real work
        deadline = time.monotonic() + 60
        progress_at_kill = 0
        while time.monotonic() < deadline:
            st = _http_json("GET",
                            f"http://127.0.0.1:{a_port}/jobs/{jid}")
            m = st.get("map", {})
            progress_at_kill = m.get("completed", 0)
            if progress_at_kill >= 1 or st.get("state") == "done":
                break
            time.sleep(0.02)

        kill_t = time.monotonic()
        active.send_signal(signal.SIGKILL)
        active.wait(timeout=30)
        _wait_status(b_port, time.monotonic() + 120, "active")
        kill_to_active = time.monotonic() - kill_t

        # first map-progress advance through the promoted daemon
        first_progress = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                st = _http_json("GET",
                                f"http://127.0.0.1:{b_port}/jobs/{jid}")
            except OSError:
                time.sleep(0.05)
                continue
            m = st.get("map", {})
            if (st.get("state") == "done"
                    or m.get("completed", 0) > progress_at_kill):
                first_progress = time.monotonic() - kill_t - kill_to_active
                break
            time.sleep(0.05)

        # drain to terminal
        deadline = time.monotonic() + 180
        state = "unknown"
        while time.monotonic() < deadline:
            try:
                st = _http_json("GET",
                                f"http://127.0.0.1:{b_port}/jobs/{jid}")
            except OSError:
                time.sleep(0.1)
                continue
            state = st.get("state", "unknown")
            if state in ("done", "failed", "cancelled"):
                break
            time.sleep(0.1)

        events = DaemonLog.read(work_root)
        promoted = [e for e in events if e["kind"] == "promoted"]
        failover_s = (promoted[-1].get("payload", {}).get("failover_s")
                      if promoted else None)
        result.update({
            "job_state": state,
            "failover_s": failover_s,
            "kill_to_active_s": round(kill_to_active, 3),
            "active_to_first_progress_s": (
                round(first_progress, 3)
                if first_progress is not None else None),
            "lease_steals": sum(1 for e in events
                                if e["kind"] == "lease_steal"),
        })
    finally:
        for p in (active, standby):
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)

    if args.check:
        result["check"] = bool(
            result.get("job_state") == "done"
            and (result.get("failover_s") or 0) > 0
        )
    print(json.dumps(result))
    if args.check and not result["check"]:
        for name in ("active.log", "standby.log"):
            p = tmp / name
            if p.exists():
                sys.stderr.write(f"--- {name} ---\n")
                sys.stderr.write(
                    p.read_bytes()[-2000:].decode("utf-8", "replace"))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

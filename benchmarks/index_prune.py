"""Shard-index routing against the grep service: warm selective queries
cost O(matching shards), not O(corpus).

ISSUE 12's acceptance bar: once shard summaries exist, a sparse-hit warm
query must beat the unindexed warm path by >= 5x (pruned shards are never
opened, never dispatched — the planner drops their map tasks), while a
dense-hit query (every shard a maybe) pays only the summary lookups.

    python benchmarks/index_prune.py [--files 48] [--file-mb 2]
        [--reps 3] [--check]

Drives the REAL surface end to end: ServiceServer HTTP API, one
in-process worker, indexed vs DGREP_INDEX=0 submits INTERLEAVED (this
box's background load swings single draws ±2x — medians over alternating
reps are the honest comparison; BASELINE.md round-8 note).  The sparse
query's needle lives in exactly one shard; the dense query's word is on
every line of every shard.  Prints exactly ONE JSON line.  ``--check``
exits 1 unless indexed and unindexed outputs are byte-identical for both
queries AND the sparse speedup clears 5x.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))

# CPU-pinned (CLAUDE.md environment rules): ASSIGN, never setdefault — and
# pop the axon plugin factory (backend discovery calls every registered
# factory even under jax_platforms=cpu).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("DGREP_NO_CALIBRATE", "1")
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

WORDS = (
    "the of and to in a is that for it as was with be by on not he this "
    "are at from or have an they which one you were all her she there "
    "would filler wikipedia philosophy"
).split()


def write_corpus(root: Path, n_files: int, file_bytes: int,
                 needle: bytes, seed: int = 9) -> list[Path]:
    """English-like shards; the needle lands in EXACTLY ONE (the sparse-
    hit shape: one shard matches, the rest are provably clean)."""
    rng = np.random.default_rng(seed)
    paths = []
    for i in range(n_files):
        lines, n = [], 0
        while n < file_bytes:
            k = int(rng.integers(3, 12))
            line = b" ".join(
                WORDS[int(rng.integers(0, len(WORDS)))].encode()
                for _ in range(k)
            )
            lines.append(line)
            n += len(line) + 1
        blob = b"\n".join(lines)[:file_bytes - 1] + b"\n"
        if i == n_files // 2:
            blob = needle + b"\n" + blob[len(needle) + 1:]
        p = root / f"f{i:05d}.txt"
        p.write_bytes(blob)
        paths.append(p)
    return paths


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--files", type=int, default=48)
    ap.add_argument("--file-mb", type=float, default=2.0)
    ap.add_argument("--sparse-pattern", default="zzyzxneedle")
    ap.add_argument("--dense-pattern", default="filler")
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved A/B reps per query; MEDIANS reported")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless outputs identical and sparse "
                         "speedup >= 5x")
    args = ap.parse_args()

    from distributed_grep_tpu.runtime.service import (
        GrepService,
        ServiceServer,
    )
    from distributed_grep_tpu.utils.config import JobConfig

    root = Path(tempfile.mkdtemp(prefix="dgrep-index-prune-"))
    (root / "in").mkdir()
    file_bytes = int(args.file_mb * (1 << 20))
    paths = write_corpus(root / "in", args.files, file_bytes,
                         args.sparse_pattern.encode())
    total = sum(p.stat().st_size for p in paths)

    service = GrepService(work_root=root / "svc")
    server = ServiceServer(service)
    server.start()
    service.start_local_workers(1)
    base = f"http://127.0.0.1:{server.port}"

    def call(method: str, path: str, body: bytes | None = None) -> dict:
        req = urllib.request.Request(f"{base}{path}", data=body,
                                     method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=600) as r:
            return json.loads(r.read())

    def submit_and_wait(pattern: str) -> tuple[float, bytes]:
        cfg = JobConfig(
            input_files=[str(p) for p in paths],
            application="distributed_grep_tpu.apps.grep_tpu",
            app_options={"pattern": pattern, "backend": "cpu"},
            n_reduce=2,
            journal=False,
        )
        t0 = time.perf_counter()
        job_id = call("POST", "/jobs",
                      cfg.to_json().encode("utf-8"))["job_id"]
        while True:
            st = call("GET", f"/jobs/{job_id}")
            if st["state"] in ("done", "failed", "cancelled"):
                break
            time.sleep(0.01)
        dt = time.perf_counter() - t0
        if st["state"] != "done":
            raise RuntimeError(f"job {job_id} ended {st['state']}: {st}")
        res = call("GET", f"/jobs/{job_id}/result")
        out = b"".join(
            Path(p).read_bytes() for p in sorted(res.get("outputs", []))
        )
        return dt, out

    def timed_leg(pattern: str, indexed: bool) -> tuple[float, bytes]:
        if indexed:
            os.environ.pop("DGREP_INDEX", None)
        else:
            os.environ["DGREP_INDEX"] = "0"
        try:
            return submit_and_wait(pattern)
        finally:
            os.environ.pop("DGREP_INDEX", None)

    # warm-up: one indexed pass per query builds every shard's summary
    # (and the compiled-model cache), so the A/B below measures routing,
    # not first-compile or summary-build cost
    for pat in (args.sparse_pattern, args.dense_pattern):
        timed_leg(pat, indexed=True)

    times: dict[str, list[float]] = {
        "sparse_on": [], "sparse_off": [], "dense_on": [], "dense_off": [],
    }
    outs: dict[str, bytes] = {}
    for _ in range(max(1, args.reps)):
        for pat, key in ((args.sparse_pattern, "sparse"),
                         (args.dense_pattern, "dense")):
            for indexed, leg in ((True, "on"), (False, "off")):
                dt, out = timed_leg(pat, indexed)
                times[f"{key}_{leg}"].append(dt)
                outs[f"{key}_{leg}"] = out

    status = call("GET", "/status")
    med = {k: statistics.median(v) for k, v in times.items()}
    sparse_speedup = (
        med["sparse_off"] / med["sparse_on"] if med["sparse_on"] else 0.0
    )
    dense_overhead = (
        (med["dense_on"] - med["dense_off"]) / med["dense_off"]
        if med["dense_off"] else 0.0
    )
    out = {
        "bench": "index_prune",
        "files": args.files,
        "bytes": total,
        "backend": jax.default_backend(),
        "reps": args.reps,
        "sparse_indexed_s": round(med["sparse_on"], 4),
        "sparse_unindexed_s": round(med["sparse_off"], 4),
        "sparse_speedup": round(sparse_speedup, 3),
        "dense_indexed_s": round(med["dense_on"], 4),
        "dense_unindexed_s": round(med["dense_off"], 4),
        "dense_overhead_pct": round(100 * dense_overhead, 2),
        "index": status.get("index", {}),
    }

    identical = (
        outs["sparse_on"] == outs["sparse_off"]
        and outs["dense_on"] == outs["dense_off"]
    )
    if args.check:
        out["check"] = "ok" if identical else "MISMATCH"

    service.stop()
    server.shutdown()

    print(json.dumps(out), flush=True)  # exactly one JSON line
    ok = identical and (not args.check or sparse_speedup >= 5.0)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

# Top-level convenience targets.  `make lint` is the whole static tier:
# the AST invariant checker (exit 1 on any violation — the repo's own
# baseline is EMPTY by policy) chained with the native tier's
# best-effort cppcheck/clang-tidy pass (no-op when neither is
# installed).  CI and editors wanting annotations: `python -m
# distributed_grep_tpu analyze --sarif`.

.PHONY: lint native test

lint:
	python -m distributed_grep_tpu analyze
	$(MAKE) -C native lint

native:
	$(MAKE) -C native

test:
	python -m pytest tests/ -x -q

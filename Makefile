# Top-level convenience targets.  `make lint` is the whole static tier:
# the AST invariant checker (exit 1 on any violation — the repo's own
# baseline is EMPTY by policy) chained with the native tier's
# best-effort cppcheck/clang-tidy pass (no-op when neither is
# installed).  CI and editors wanting annotations: `python -m
# distributed_grep_tpu analyze --sarif`.

.PHONY: lint native test chaos trend caches

lint:
	python -m distributed_grep_tpu analyze
	$(MAKE) -C native lint

native:
	$(MAKE) -C native

test:
	python -m pytest tests/ -x -q

# The chaos tier standalone: real `dgrep serve` subprocesses SIGKILLed
# mid-stream (incl. the round-18 active/standby failover cases) with
# FaultTransport-injected network faults.  The tests zero
# DGREP_RPC_RETRIES themselves before daemon teardown (retry schedules
# are built per call from the env) — no extra env needed here.
chaos:
	python -m pytest tests/test_chaos.py -q

# The warm-tier receipts end to end: corpus cache (round 7), shard
# index (round 14), query-result cache (round 20) — each `--check`
# gates byte identity plus its tier's speedup floor.  CPU-runnable;
# each prints exactly one JSON line.
caches:
	python benchmarks/corpus_resident.py --check
	python benchmarks/index_prune.py --check
	python benchmarks/result_cache.py --check

# Round-over-round bench trajectory (BENCH_r*.json) as one JSON line +
# a markdown table.  Reporting only — no gating (this box's background
# load swings ~2x; BASELINE.md's interleaved A/B medians are the honest
# comparisons).
trend:
	python tools/bench_trend.py
